"""DistExecutor: interpret a fragmented plan over a jax device Mesh.

Reference: the worker-side execution of exchanges — operator/
PartitionedOutputOperator.java (hash rows -> partition -> serialize ->
HTTP buffer) and operator/ExchangeOperator.java (fetch + deserialize) —
plus LocalExecutionPlanner wiring. TPU-native redesign: a "page" is ONE
global jax.Array per column, sharded row-wise across the mesh
(NamedSharding over axis "d"), and every exchange is an XLA collective
compiled into the neighboring kernel via shard_map:

    repartition -> per-shard bucketing + lax.all_to_all
    broadcast   -> lax.all_gather(tiled) to a replicated page
    gather      -> same collective; semantically the SINGLE partitioning
                   (every device holds the full stream and runs the final
                   stage redundantly — replicated compute is free compared
                   to leaving devices idle)

Shard-local operators reuse the single-device kernels unchanged inside
shard_map bodies — the Driver loop compiled away, the shuffle compiled in.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from presto_tpu import types as T
from presto_tpu.exec import agg_states as S
from presto_tpu.exec import plan as P
from presto_tpu.exec import xfer as XF
from presto_tpu.exec.executor import (
    Executor,
    _final_agg_page,
    _final_global_agg,
    _next_pow2,
    _null_blocks,
    _partial_agg_page,
    _partial_global_agg,
    _probe_join_page,
    _semi_join_page,
)
from presto_tpu.ops import hashing as H
from presto_tpu.ops import keys as K
from presto_tpu.ops.compact import compact_indices, concat_all, scatter_column
from presto_tpu.page import Block, Page

SHARDED = "sharded"
REPLICATED = "replicated"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    # xfercheck: raw-ok - object array of device HANDLES; no bytes cross
    return Mesh(np.array(devs[:n]), ("d",))


class DistExecutor(Executor):
    """Executes plans produced by dist.fragmenter.add_exchanges.

    Page distribution is tracked statically per node ("sharded" over the
    mesh vs "replicated"); replicated subtrees run the inherited single-
    stream code paths (XLA replicates the compute across devices), sharded
    nodes run shard_map-wrapped kernels.
    """

    def __init__(self, catalogs, mesh: Mesh, **kw):
        super().__init__(catalogs, **kw)
        self.mesh = mesh
        self.D = int(mesh.devices.size)
        self._dist_cache: Dict[int, str] = {}
        # hash_partition_count session property: devices that RECEIVE
        # repartitioned rows (0 = whole mesh). Routing and residue
        # filters share _route_devices so both sides of a partitioned
        # stage agree on the partition function.
        self.hash_partitions = 0

    def _route_devices(self) -> int:
        """Devices used for repartitioned stages (reference:
        hash_partition_count): hash routing targets devices
        0..P-1; the whole mesh still executes the programs."""
        hp = int(self.hash_partitions or 0)
        return min(hp, self.D) if hp > 0 else self.D

    # ------------------------------------------------- memory governor
    def _budget(self) -> int:
        """Mesh-wide device-memory budget: the per-chip share (the
        inherited resolution — one chip's HBM minus headroom, or the
        session's device_memory_budget per chip) times the mesh size.
        The dominant governed buffers — partitioned join builds,
        repartitioned aggregation state — are sharded row-wise across
        the mesh, so the mesh collectively holds D chips' shares.
        Replicated (broadcast) builds are bounded separately by the
        stats-driven broadcast decision, which uses the PER-CHIP share
        (runner._session_dist_options -> fragmenter.broadcast_bytes)."""
        return super()._budget() * self.D

    # -------------------------------------------- collective dispatch
    def _fenced(self, fn):
        """Serialize collective programs on the CPU backend.

        The in-process CPU runtime schedules enqueued executables by
        DATAFLOW READINESS, not dispatch order: two in-flight programs
        that both contain cross-device collectives can start in
        different orders on different virtual devices — device 0 enters
        program B's all-reduce rendezvous while devices 1..7 wait in
        program A's, and the rendezvous aborts after its timeout
        (MULTICHIP_r05 rc=134: TPC-DS Q17's windowed generated-join
        `psum` interleaved with the dim-join pipeline's gathers,
        "Expected 8 threads to join the rendezvous, but only 1
        arrived"). Blocking on each collective program's outputs before
        the next one can be dispatched enforces ONE consistent
        execution order across all devices. TPU per-device queues
        execute strictly in dispatch order, so the fence is CPU-only
        and costs hardware nothing — the deferred-sync discipline
        (Executor.__init__) is a TPU-runtime concern and unaffected."""
        if jax.default_backend() != "cpu":
            return fn

        def fenced(*args):
            out = fn(*args)
            # xfercheck: raw-ok - sync fence (no copy): pins collective
            jax.block_until_ready(out)  # rendezvous order on CPU
            return out

        return fenced

    # ---------------------------------------------------------- dist tags
    def dist(self, node: P.PhysicalNode) -> str:
        # keyed by id() with the node itself retained: a bare id key goes
        # stale when a garbage-collected plan node's address is reused by
        # a later plan (observed as flaky distributed-vs-single mismatches)
        key = id(node)
        hit = self._dist_cache.get(key)
        if hit is None or hit[0] is not node:
            self._dist_cache[key] = (node, self._compute_dist(node))
        return self._dist_cache[key][1]

    def _compute_dist(self, node) -> str:
        if isinstance(node, P.TableScan):
            return SHARDED
        if isinstance(node, P.Values):
            return REPLICATED
        if isinstance(node, P.Exchange):
            return SHARDED if node.kind == "repartition" else REPLICATED
        if isinstance(node, (P.HashJoin, P.CrossJoin)):
            return self.dist(node.left)
        if isinstance(node, P.Union):
            return self.dist(node.sources[0])
        children = node.children()
        return self.dist(children[0]) if children else REPLICATED

    # --------------------------------------------- cache residency
    def _cache_subtree_ok(self, node: P.PhysicalNode) -> bool:
        """Mesh-path cache residency (ISSUE 15 satellite, ROADMAP
        item 6 remainder): only REPLICATED subtrees may become cache
        points — their pages are ordinary replicated arrays a host
        replay can reproduce, so a mesh query with an uncacheable
        root (volatile filter, system branch) still caches its
        expensive gathered interior instead of nothing at all.
        Sharded subtrees' pages are mesh-sharded global arrays; a
        host replay could not rebuild their shard layout."""
        return self.dist(node) == REPLICATED

    def _sink_chain_ids(self, node) -> frozenset:
        """Mesh host-sink chain: besides the Output pass-through, a
        gather/broadcast Exchange over an already-REPLICATED source
        is a verbatim pass-through on this executor
        (_exec_exchange yields self.pages(source) unchanged), so a
        cache point below it can serve host pages straight to result
        decode — the mesh-local handoff applied to replays, with
        ZERO h2d/d2h crossings on the hit (transfer-ledger pinned in
        tests/test_result_cache.py)."""
        ids = {id(node)}
        while True:
            if isinstance(node, P.Output):
                node = node.source
            elif (isinstance(node, P.Exchange)
                    and node.kind in ("gather", "broadcast")
                    and self.dist(node.source) == REPLICATED):
                node = node.source
            else:
                break
            ids.add(id(node))
        return frozenset(ids)

    def _stage_replay(self, page: Page) -> Page:
        """Replayed host pages commit as mesh-REPLICATED arrays (not
        device-0 singletons): consumers above a replicated cache
        point may be shard_map programs with replicated in_specs
        (residue repartition, broadcast joins) that require a
        consistent placement across every mesh device."""
        return XF.to_device(
            page, spec=NamedSharding(self.mesh, PS()),
            label="cache-replay")

    # ------------------------------------------------------------- pages
    def _pages_impl(self, node: P.PhysicalNode) -> Iterator[Page]:
        if isinstance(node, P.Exchange):
            yield from self._exec_exchange(node)
            return
        if self.dist(node) == REPLICATED and all(
            self.dist(c) == REPLICATED for c in node.children()
        ):
            yield from super()._pages_impl(node)
            return
        if isinstance(node, P.TableScan):
            yield from self._scan_sharded(node)
            return
        if isinstance(node, P.Filter):
            from presto_tpu.expr.eval import evaluate_filter

            fn = self._shard_page_kernel(
                ("d_filter", node.predicate),
                lambda page, _pred=node.predicate: evaluate_filter(
                    _pred, page, jnp
                ),
            )
            for page in self.pages(node.source):
                yield fn(page)
            return
        if isinstance(node, P.Project):
            from presto_tpu.exec.executor import _project_page

            fn = self._shard_page_kernel(
                ("d_project", node.exprs),
                functools.partial(_project_page, node.exprs),
            )
            for page in self.pages(node.source):
                yield fn(page)
            return
        if isinstance(node, P.Aggregation):
            yield from self._dist_aggregation(node)
            return
        if isinstance(node, P.HashJoin):
            yield from self._dist_join(node)
            return
        if isinstance(node, P.CrossJoin):
            yield from self._dist_cross_join(node)
            return
        if isinstance(node, P.UniqueId):
            yield from self._dist_unique_id(node)
            return
        if isinstance(node, P.Unnest):
            from presto_tpu.exec.executor import _unnest_page

            for page in self.pages(node.source):
                dic = page.block(node.array_channel).dictionary
                fn = self._shard_page_kernel(
                    ("d_unnest", node.array_channel, node.element_type,
                     node.with_ordinality, dic),
                    functools.partial(
                        _unnest_page, node.array_channel,
                        node.element_type, node.with_ordinality,
                    ),
                )
                yield fn(page)
            return
        if isinstance(node, P.GroupId):
            from presto_tpu.exec.executor import _group_id_page

            fns = [
                self._shard_page_kernel(
                    ("d_groupid", node.key_channels, mask, si),
                    functools.partial(_group_id_page,
                                      node.key_channels, mask, si),
                )
                for si, mask in enumerate(node.set_masks)
            ]
            for page in self.pages(node.source):
                for fn in fns:
                    yield fn(page)
            return
        if isinstance(node, P.Union):
            for src in node.sources:
                yield from self.pages(src)
            return
        if isinstance(node, P.Output):
            yield from self.pages(node.source)
            return
        raise TypeError(
            f"DistExecutor: node {type(node).__name__} requires a "
            f"replicated input (fragmenter should have inserted a gather)"
        )

    # ----------------------------------------------------------- helpers
    def _lazy_probe_ok(self, node: P.PhysicalNode) -> bool:
        """Late materialization only along fully-replicated probe
        spines: sharded subtrees route through shard_map paths that
        speak materialized Pages (their exchanges/collectives cannot
        carry a host-side indirection descriptor)."""
        return (
            super()._lazy_probe_ok(node)
            and self.dist(node) == REPLICATED
            and all(
                self.dist(c) == REPLICATED for c in node.children()
            )
        )

    def _shard_page_kernel(self, key, fn):
        """shard_map-wrap a pure page->page kernel (shard-local map)."""
        if key not in self._jit_cache:
            body = jax.shard_map(
                fn, mesh=self.mesh, in_specs=(PS("d"),),
                out_specs=PS("d"), check_vma=False,
            )
            self._jit_cache[key] = jax.jit(body)
        return self._jit_cache[key]

    # -------------------------------------------------------------- scan
    def _scan_sharded(self, node: P.TableScan) -> Iterator[Page]:
        conn = self.catalogs[node.catalog]
        schema = conn.table_schema(node.table)
        names = tuple(node.columns)
        splits = conn.splits(node.table, target_rows=self.page_rows)
        n = splits[0].row_count
        total = splits[-1].start_row + splits[-1].row_count
        body = conn.gen_body(node.table, n, names)
        if body is None:
            yield from self._scan_staged(node, conn, names)
            return
        dicts = getattr(conn, "_dicts", {}).get(node.table, {})

        def gen_local(start_arr):
            start = start_arr[0]
            datas, valid = body(start)
            # rounds are padded to D devices; slots past the table are
            # masked out here (the generator itself has no bound)
            in_range = (
                start + jnp.arange(n, dtype=jnp.int64)
            ) < jnp.int64(total)
            return datas, valid & in_range

        key = ("d_scan", node.catalog, node.table, names, n)
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(jax.shard_map(
                gen_local, mesh=self.mesh,
                in_specs=(PS("d"),), out_specs=PS("d"), check_vma=False,
            ))
        fn = self._jit_cache[key]

        starts = [s.start_row for s in splits]
        spec = NamedSharding(self.mesh, PS("d"))
        for r in range(0, len(starts), self.D):
            chunk = starts[r:r + self.D]
            real = len(chunk)
            # pad the tail round; padded starts generate fully-masked rows
            chunk = chunk + [total] * (self.D - len(chunk))
            start_arr = XF.to_device(
                # xfercheck: raw-ok - chunk is a host list of split starts
                np.asarray(chunk, dtype=np.int64),
                spec=spec, label="split-starts",
            )
            datas, valid = fn(start_arr)
            # launch amortization (ROOFLINE §7): a mesh round is one
            # program covering D splits — the same accounting the
            # split-batched local scan reports
            self.program_launches += 1
            self.splits_scanned += real
            blocks = tuple(
                Block(
                    data=data,
                    type=schema.column_type(nm),
                    nulls=None,
                    dictionary=dicts.get(nm),
                )
                for nm, data in zip(names, datas)
            )
            yield Page(blocks=blocks, valid=valid)

    def _scan_staged(self, node, conn, names) -> Iterator[Page]:
        """Host-page connectors (e.g. memory connector): stage each round
        of host splits onto the mesh devices directly."""
        spec = NamedSharding(self.mesh, PS("d"))
        pages = list(conn.pages(node.table, names,
                                target_rows=self.page_rows))
        if not pages:
            return
        cap = max(p.capacity for p in pages)
        for r in range(0, len(pages), self.D):
            chunk = pages[r:r + self.D]
            yield _stack_to_mesh(chunk, cap, self.D, spec)

    # --------------------------------------------------------- exchanges
    def _exec_exchange(self, node: P.Exchange) -> Iterator[Page]:
        src_dist = self.dist(node.source)
        if node.kind in ("gather", "broadcast"):
            if src_dist == REPLICATED:
                yield from self.pages(node.source)
                return
            # compiled collective over the mesh: the exchange never
            # leaves the device — the same zero-crossing contract the
            # spooled mesh-local fast path counts (ISSUE 13)
            self.count_mesh_local()
            fn = self._gather_fn()
            for page in self.pages(node.source):
                yield fn(page)
            return
        if node.kind == "repartition":
            self.count_mesh_local()
            if src_dist == REPLICATED:
                # replicated -> sharded: each device keeps its hash
                # residues (deterministic disjoint split, no comms)
                fn = self._residue_fn(node.keys)
            else:
                fn = self._repartition_fn(node.keys)
            for page in self.pages(node.source):
                out, overflow = fn(page)
                self._pending_overflow.append(overflow)
                yield out
            return
        raise ValueError(f"unknown exchange kind {node.kind!r}")

    def _gather_fn(self):
        key = ("d_gather",)
        if key not in self._jit_cache:
            def body(page):
                return jax.tree.map(
                    lambda x: jax.lax.all_gather(x, "d", tiled=True), page
                )

            # check_vma=False: all_gather(tiled) output IS replicated but
            # jax's varying-axis inference cannot prove it
            self._jit_cache[key] = self._fenced(jax.jit(jax.shard_map(
                body, mesh=self.mesh, in_specs=(PS("d"),), out_specs=PS(),
                check_vma=False,
            )))
        return self._jit_cache[key]

    def _key_hash(self, page: Page, keys: Tuple[int, ...]) -> jnp.ndarray:
        blocks = [page.block(c) for c in keys]
        cols, nulls = K.block_key_columns(blocks)
        return H.hash_columns(cols, nulls)

    def _repartition_fn(self, keys: Tuple[int, ...]):
        """hash(keys) % D routing via lax.all_to_all — the
        PartitionedOutputOperator -> ExchangeOperator data plane as one
        compiled collective (SURVEY §3.3 north-star mapping).

        The landing-zone capacity rides the boosted-retry ladder: a
        skewed key routing most rows to one device overflows the 2R
        default and the query retries with 4x landing capacity (SURVEY
        §6.7 — correctness under skew never depends on balance)."""
        D = self.D
        P = self._route_devices()  # hash_partition_count (<= D)
        boost = self._capacity_boost

        def body(page: Page):
            R = page.capacity  # local rows per device
            h = self._key_hash(page, keys)
            tgt = (h % jnp.uint64(P)).astype(jnp.int32)
            tgt = jnp.where(page.valid, tgt, D)
            # stable-sort rows by destination, compute position within
            # each destination bucket
            perm = jnp.argsort(tgt, stable=True)
            st = tgt[perm]
            first = jnp.searchsorted(
                st, jnp.arange(D, dtype=st.dtype), side="left"
            )
            pos = jnp.arange(R, dtype=jnp.int64) - first[
                jnp.clip(st, 0, D - 1)].astype(jnp.int64)
            # send layout [D, R]: slot (dest, pos); invalid rows drop
            slot = jnp.where(
                (st < D) & (pos < R),
                st.astype(jnp.int64) * R + pos,
                jnp.int64(D * R),
            )

            def to_send(x):
                out = jnp.zeros((D * R,), dtype=x.dtype)
                return out.at[slot].set(x[perm], mode="drop").reshape(D, R)

            sent = jax.tree.map(to_send, page)  # includes valid
            recv = jax.tree.map(
                lambda x: jax.lax.all_to_all(
                    x, "d", split_axis=0, concat_axis=0, tiled=False
                ),
                sent,
            )
            flat = jax.tree.map(
                lambda x: x.reshape((D * R,) + x.shape[2:]), recv
            )
            flat_valid = flat.valid
            # compact the D*R landing zone back to a bounded local page
            out_cap = min(D * R, _next_pow2(2 * R * boost))
            targets, out_valid, num = compact_indices(flat_valid, out_cap)
            blocks = []
            for blk in flat.blocks:
                if isinstance(blk.data, tuple):
                    data = tuple(
                        scatter_column(d, targets, out_cap)
                        for d in blk.data
                    )
                else:
                    data = scatter_column(blk.data, targets, out_cap)
                nulls = (
                    scatter_column(blk.nulls, targets, out_cap)
                    if blk.nulls is not None else None
                )
                blocks.append(blk.with_data(data, nulls=nulls))
            out = Page(blocks=tuple(blocks), valid=out_valid)
            overflow = jax.lax.psum(
                (num > out_cap).astype(jnp.int32), "d") > 0
            return out, overflow

        key = ("d_repart", keys, self.D, P, boost)
        if key not in self._jit_cache:
            self._jit_cache[key] = self._fenced(jax.jit(jax.shard_map(
                body, mesh=self.mesh, in_specs=(PS("d"),),
                out_specs=(PS("d"), PS()), check_vma=False,
            )))
        return self._jit_cache[key]

    def _residue_fn(self, keys: Tuple[int, ...]):
        """Replicated -> sharded: device i keeps rows with
        hash(keys) % D == i (no data movement; the replica is local)."""
        P = self._route_devices()  # must agree with _repartition_fn

        def body(page: Page):
            me = jax.lax.axis_index("d")
            h = self._key_hash(page, keys)
            mine = (h % jnp.uint64(P)).astype(jnp.int32) == me
            out = page.with_valid(page.valid & mine)
            return out, jnp.asarray(False)

        key = ("d_residue", keys, self.D, P)
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(jax.shard_map(
                body, mesh=self.mesh, in_specs=(PS(),),
                out_specs=(PS("d"), PS()), check_vma=False,
            ))
        return self._jit_cache[key]

    # ------------------------------------------------------- aggregation
    def _dist_aggregation(self, node: P.Aggregation) -> Iterator[Page]:
        src_dist = self.dist(node.source)
        if node.step == "partial" and src_dist == SHARDED:
            in_types = self._agg_in_types(node)
            layouts = tuple(
                tuple(S.state_layout(s.function, t))
                for s, t in zip(node.aggregates, in_types)
            )
            if not node.group_channels:
                fn = self._shard_page_kernel(
                    ("d_gagg_partial", node.aggregates, layouts),
                    functools.partial(
                        _partial_global_agg, node.aggregates, layouts
                    ),
                )
                for page in self.pages(node.source):
                    yield fn(page)
                return
            cap = _next_pow2(node.capacity * self._capacity_boost)
            max_iters = 64 * self._capacity_boost

            def make(local_cap):
                def body(page):
                    out, ovf = _partial_agg_page(
                        node.group_channels, node.aggregates, layouts,
                        page, local_cap, max_iters,
                    )
                    return out, jax.lax.psum(
                        ovf.astype(jnp.int32), "d") > 0

                return self._fenced(jax.jit(jax.shard_map(
                    body, mesh=self.mesh, in_specs=(PS("d"),),
                    out_specs=(PS("d"), PS()), check_vma=False,
            )))

            for page in self.pages(node.source):
                local_cap = min(
                    cap, _next_pow2(page.capacity // self.D)
                )
                # canonical: the estimate-bearing node stays OUT of the
                # key (exec/shapes.py discipline — content only)
                key = ("d_agg_partial", node.group_channels,
                       node.aggregates, layouts, local_cap, max_iters)
                if key not in self._jit_cache:
                    self._jit_cache[key] = make(local_cap)
                out, overflow = self._jit_cache[key](page)
                self._pending_overflow.append(overflow)
                yield out
            return
        if node.step == "final" and src_dist == SHARDED:
            # repartitioned state pages: keys are co-located per device,
            # final agg runs shard-locally
            origin = self._partial_origin(node)
            in_types = self._agg_in_types(origin)
            layouts = tuple(
                tuple(S.state_layout(s.function, t))
                for s, t in zip(node.aggregates, in_types)
            )
            pages = list(self.pages(node.source))
            if not pages:
                return
            local_caps = tuple(p.capacity // self.D for p in pages)
            fcap = min(
                _next_pow2(node.capacity * self._capacity_boost),
                _next_pow2(sum(local_caps)),
            )
            max_iters = 64 * self._capacity_boost

            def body(*pgs):
                merged = concat_all(pgs) if len(pgs) > 1 else pgs[0]
                out, ovf = _final_agg_page(
                    node.group_channels, node.aggregates, layouts,
                    tuple(in_types), merged, fcap, max_iters,
                )
                return out, jax.lax.psum(ovf.astype(jnp.int32), "d") > 0

            key = ("d_agg_final", node.group_channels, node.aggregates,
                   layouts, tuple(in_types), local_caps, fcap,
                   max_iters)
            if key not in self._jit_cache:
                self._jit_cache[key] = self._fenced(jax.jit(jax.shard_map(
                    body, mesh=self.mesh,
                    in_specs=tuple(PS("d") for _ in pages),
                    out_specs=(PS("d"), PS()), check_vma=False,
            )))
            out, overflow = self._jit_cache[key](*pages)
            self._pending_overflow.append(overflow)
            yield out
            return
        # replicated input: inherited single-stream paths
        yield from super()._exec_aggregation(node)

    # -------------------------------------------------------------- join
    def _dist_join(self, node: P.HashJoin) -> Iterator[Page]:
        dl, dr = self.dist(node.left), self.dist(node.right)
        gj = self._generated_join_info(node, self.output_types(node.left))
        if gj is not None:
            # build-free generated join is embarrassingly SPMD: each
            # device inverts its shard's probe keys and GENERATES the
            # carried build columns locally — no broadcast, no
            # repartition, no build materialization on any device
            yield from self._dist_join_generated(node, gj, dl)
            return
        if dl == REPLICATED and dr == REPLICATED:
            yield from super()._exec_join(node)
            return
        yield from self._dist_join_materialized(node, dl, dr)

    def _dist_join_generated(self, node: P.HashJoin, info, dl
                             ) -> Iterator[Page]:
        self.generated_joins_used += 1
        kern, windowed = self.generated_join_kernel(node, info)
        spec = PS("d") if dl == SHARDED else PS()
        if not windowed:
            key = ("d_genjoin", node, dl)
            if key not in self._jit_cache:
                self._jit_cache[key] = jax.jit(jax.shard_map(
                    kern, mesh=self.mesh, in_specs=(spec,),
                    out_specs=spec, check_vma=False,
                ))
            for page in self.pages(node.left):
                yield self._jit_cache[key](page)
            return

        def win_body(page):
            out, multi = kern(page)
            return out, jax.lax.psum(multi.astype(jnp.int32), "d") > 0

        key = ("d_genjoin_win", node, dl)
        if key not in self._jit_cache:
            # fenced: the windowed multi-match psum is THE collective
            # whose free interleaving deadlocked MULTICHIP_r05 (Q17)
            self._jit_cache[key] = self._fenced(jax.jit(jax.shard_map(
                win_body, mesh=self.mesh, in_specs=(spec,),
                out_specs=(spec, PS()), check_vma=False,
            )))
        for page in self.pages(node.left):
            out, multi = self._jit_cache[key](page)
            self._pending_overflow.append(multi)
            yield out

    def _dist_join_materialized(self, node: P.HashJoin, dl, dr
                                ) -> Iterator[Page]:
        # build side: replicated (broadcast) or sharded (partitioned)
        build_pages = list(self.pages(node.right))
        right_types = self.output_types(node.right)
        left_types = self.output_types(node.left)
        if not build_pages:
            from presto_tpu.exec.executor import _empty_page

            build_pages = [_empty_page(right_types, cap=self.D * 8)]
        build_all = (
            concat_all(build_pages) if len(build_pages) > 1
            else build_pages[0]
        )
        build_spec = PS() if dr == REPLICATED else PS("d")
        probe_spec = PS("d") if dl == SHARDED else PS()

        if node.join_type in ("semi", "anti"):
            def semi_body(page, build):
                return _semi_join_page(
                    node.left_keys, node.right_keys, page, build
                )

            key = ("d_semi", node, build_all.capacity)
            if key not in self._jit_cache:
                self._jit_cache[key] = jax.jit(jax.shard_map(
                    semi_body, mesh=self.mesh,
                    in_specs=(probe_spec, build_spec),
                    out_specs=PS("d") if dl == SHARDED else PS(), check_vma=False,
            ))
            for page in self.pages(node.left):
                yield self._jit_cache[key](page, build_all)
            return

        local_build_cap = (
            build_all.capacity if dr == REPLICATED
            else build_all.capacity // self.D
        )
        matched_acc = None
        probe_pages = list(self.pages(node.left))
        for page in probe_pages:
            local_probe = (
                page.capacity // self.D if dl == SHARDED
                else page.capacity
            )
            oc = _next_pow2(
                max(local_probe, local_build_cap) * 2
                * self._capacity_boost
            )

            def probe_body(pg, build, oc=oc):
                from presto_tpu.exec.executor import _build_join_index

                index = _build_join_index(
                    node.left_keys, node.right_keys, pg, build
                )
                out, matched, ovf = _probe_join_page(
                    node.left_keys, node.right_keys, node.join_type,
                    False, pg, build, index, oc,
                )
                ovf = jax.lax.psum(ovf.astype(jnp.int32), "d") > 0
                if dr == REPLICATED:
                    # matched refers to replicated build rows: OR the
                    # per-device views so outer emission sees every match
                    matched = jax.lax.psum(
                        matched.astype(jnp.int32), "d") > 0
                return out, matched, ovf

            key = ("d_probe", node, page.capacity, build_all.capacity,
                   oc, dl, dr)
            if key not in self._jit_cache:
                self._jit_cache[key] = self._fenced(jax.jit(jax.shard_map(
                    probe_body, mesh=self.mesh,
                    in_specs=(probe_spec, build_spec),
                    out_specs=(
                        PS("d"),
                        PS() if dr == REPLICATED else PS("d"),
                        PS(),
                    ), check_vma=False,
            )))
            out, matched, overflow = self._jit_cache[key](page, build_all)
            self._pending_overflow.append(overflow)
            matched_acc = (
                matched if matched_acc is None else matched_acc | matched
            )
            yield out
        if node.join_type in ("right", "full"):
            yield self._outer_build_rows(
                node, build_all, matched_acc, left_types, dr
            )

    def _outer_build_rows(self, node, build_all, matched, left_types, dr):
        """Unmatched build rows with a null probe side. Replicated builds
        are emitted once per hash residue so the sharded stream holds each
        row exactly once."""
        D = self.D

        def body(build, m):
            unmatched = build.valid & ~m
            if dr == REPLICATED:
                me = jax.lax.axis_index("d")
                idx = jnp.arange(build.capacity, dtype=jnp.int32)
                unmatched = unmatched & ((idx % D) == me)
            nulls = _null_blocks(left_types, build.capacity)
            return Page(
                blocks=tuple(nulls) + build.blocks, valid=unmatched
            )

        key = ("d_outer", node, build_all.capacity, dr)
        if key not in self._jit_cache:
            bspec = PS() if dr == REPLICATED else PS("d")
            self._jit_cache[key] = jax.jit(jax.shard_map(
                body, mesh=self.mesh, in_specs=(bspec, bspec),
                out_specs=PS("d"), check_vma=False,
            ))
        return self._jit_cache[key](build_all, matched)

    def _dist_cross_join(self, node: P.CrossJoin) -> Iterator[Page]:
        from presto_tpu.exec.executor import _cross_join_page, compact_page

        # fragmenter guarantees the right side is replicated
        right_pages = list(self.pages(node.right))
        if not right_pages:
            return
        build_all = concat_all(right_pages)
        bcap = min(
            _next_pow2(build_all.capacity),
            _next_pow2(4096 * self._capacity_boost),
        )
        self._pending_overflow.append(build_all.num_rows() > bcap)
        build = compact_page(build_all, bcap)

        def body(pg, b):
            return _cross_join_page(pg, b)

        for page in self.pages(node.left):
            key = ("d_cross", node, page.capacity, bcap)
            if key not in self._jit_cache:
                self._jit_cache[key] = jax.jit(jax.shard_map(
                    body, mesh=self.mesh, in_specs=(PS("d"), PS()),
                    out_specs=PS("d"), check_vma=False,
            ))
            yield self._jit_cache[key](page, build)

    def _dist_unique_id(self, node: P.UniqueId) -> Iterator[Page]:
        # globally-unique bigint per row: device index in the high bits
        offset = 0

        def body(page, off):
            me = jax.lax.axis_index("d").astype(jnp.int64)
            ids = (
                (me << jnp.int64(40))
                + off
                + jnp.arange(page.capacity, dtype=jnp.int64)
            )
            blk = Block(data=ids, type=T.BIGINT)
            return Page(blocks=page.blocks + (blk,), valid=page.valid)

        for page in self.pages(node.source):
            key = ("d_uid", node, page.capacity)
            if key not in self._jit_cache:
                self._jit_cache[key] = jax.jit(jax.shard_map(
                    body, mesh=self.mesh, in_specs=(PS("d"), PS()),
                    out_specs=PS("d"), check_vma=False,
            ))
            yield self._jit_cache[key](page, jnp.int64(offset))
            offset += page.capacity


def _stack_to_mesh(pages: List[Page], cap: int, D: int, spec) -> Page:
    """Stage up to D host pages as one mesh-sharded global page (host
    data path for connectors without on-device generators)."""
    import numpy as _np

    padded: List[Optional[Page]] = list(pages) + [None] * (D - len(pages))

    first = pages[0]
    blocks = []
    for ch in range(first.channel_count):
        datas, nulls_l = [], []
        any_nulls = any(
            p is not None and p.block(ch).nulls is not None for p in padded
        )
        for p in padded:
            if p is None:
                blk0 = first.block(ch)
                if isinstance(blk0.data, tuple):
                    datas.append(tuple(
                        _np.zeros(cap, d.dtype) for d in blk0.data
                    ))
                else:
                    datas.append(_np.zeros(cap, blk0.data.dtype))
                nulls_l.append(_np.ones(cap, bool))
                continue
            blk = p.block(ch)
            if isinstance(blk.data, tuple):
                datas.append(tuple(
                    _pad_np(XF.np_host(d), cap) for d in blk.data
                ))
            else:
                datas.append(_pad_np(XF.np_host(blk.data), cap))
            nulls_l.append(
                _pad_np(XF.np_host(blk.nulls), cap)
                if blk.nulls is not None else _np.zeros(cap, bool)
            )
        blk0 = first.block(ch)
        if isinstance(blk0.data, tuple):
            data = tuple(
                XF.to_device(
                    _np.concatenate([d[i] for d in datas]),
                    spec=spec, label="stack-to-mesh",
                )
                for i in range(len(blk0.data))
            )
        else:
            data = XF.to_device(_np.concatenate(datas), spec=spec,
                                label="stack-to-mesh")
        nulls = (
            XF.to_device(_np.concatenate(nulls_l), spec=spec,
                         label="stack-to-mesh")
            if any_nulls else None
        )
        blocks.append(Block(
            data=data, type=blk0.type, nulls=nulls,
            dictionary=blk0.dictionary,
        ))
    valid = _np.concatenate([
        _pad_np(XF.np_host(p.valid), cap) if p is not None
        else _np.zeros(cap, bool)
        for p in padded
    ])
    return Page(blocks=tuple(blocks),
                valid=XF.to_device(valid, spec=spec,
                                   label="stack-to-mesh"))


def _pad_np(arr, cap):
    if arr.shape[0] == cap:
        return arr
    out = np.zeros((cap,) + arr.shape[1:], arr.dtype)
    out[: arr.shape[0]] = arr
    return out


# ---------------------------------------------------------------------
# ICI exchange plane (ISSUE 18): lower a spooled repartition edge to an
# in-program lax.all_to_all when the producer stage's spools and the
# consumer stage's readers are co-resident on ONE process mesh. The
# spool plane stays authoritative for DCN-remote consumers and for
# replay/fault recovery; this plane only replaces the
# partition -> serialize -> HTTP -> deserialize -> re-stage hop with a
# single collective over the device interconnect.


def ici_exchange_supported(nparts: int, pages) -> bool:
    """Static shape gate for `ici_exchange_pages`: the exchange maps
    partition p to mesh device p, so the partition count must be a
    power of two that the local device pool can host, and every page's
    capacity (a ladder power of two >= 8) must shard evenly across it.
    Anything else stays on the spool plane — a shape, never an error."""
    if nparts < 2 or (nparts & (nparts - 1)) != 0:
        return False
    if nparts > len(jax.devices()):
        return False
    return all(p.capacity % nparts == 0 for p in pages)


_ICI_MESHES: Dict[int, Mesh] = {}

# compiled all_to_all exchange programs, keyed by exchange geometry
# (see _ici_program: process-level so per-query coordinator executors
# share warm programs the way the shapes ladder intends)
_ICI_PROGRAMS: Dict[tuple, object] = {}


def _ici_mesh(d: int) -> Mesh:
    """One cached Mesh per device count: the compiled exchange
    programs close over the mesh object, so handing every jit-cache
    hit the SAME mesh keeps shard_map from re-validating placements."""
    if d not in _ICI_MESHES:
        _ICI_MESHES[d] = make_mesh(d)
    return _ICI_MESHES[d]


def _ici_program(ex, mesh: Mesh, keys: Tuple[int, ...], dicts,
                 nluts: int, d: int, out_cap: int):
    """The per-page exchange collective: shard-local splitmix64
    routing + all_to_all + compaction to the ladder landing capacity.
    Mirrors DistExecutor._repartition_fn, with two deltas: the routing
    hash is dist/spool.device_row_hash_u64 — BIT-IDENTICAL to the
    spool plane's host and device partitioners, so a mid-query
    fallback (or one side of a co-partitioned join taking the spool
    path) lands every row in the same partition — and the landing
    capacity is shapes.exchange_partition_cap, the SAME bucket the
    spool partitioner compacts to, so consumer jit keys cannot tell
    the planes apart."""
    from presto_tpu.dist import spool as SPOOL

    def body(pg: Page, *vhs):
        vh_by_key = iter(vhs)
        full = tuple(next(vh_by_key) if dct is not None else None
                     for dct in dicts)
        r = pg.capacity  # local rows per device
        h = SPOOL.device_row_hash_u64(pg, keys, full)
        tgt = (h % jnp.uint64(d)).astype(jnp.int32)
        tgt = jnp.where(pg.valid, tgt, d)
        # stable-sort rows by destination partition (== destination
        # device), position within each destination bucket
        perm = jnp.argsort(tgt, stable=True)
        st = tgt[perm]
        first = jnp.searchsorted(
            st, jnp.arange(d, dtype=st.dtype), side="left"
        )
        pos = jnp.arange(r, dtype=jnp.int64) - first[
            jnp.clip(st, 0, d - 1)].astype(jnp.int64)
        slot = jnp.where(
            (st < d) & (pos < r),
            st.astype(jnp.int64) * r + pos,
            jnp.int64(d * r),
        )

        def to_send(x):
            out = jnp.zeros((d * r,), dtype=x.dtype)
            return out.at[slot].set(x[perm], mode="drop").reshape(d, r)

        sent = jax.tree.map(to_send, pg)  # includes valid
        recv = jax.tree.map(
            lambda x: jax.lax.all_to_all(
                x, "d", split_axis=0, concat_axis=0, tiled=False
            ),
            sent,
        )
        flat = jax.tree.map(
            lambda x: x.reshape((d * r,) + x.shape[2:]), recv
        )
        # compact the d*r landing zone to the spool plane's partition
        # bucket; skew joins the boosted-retry ladder via the
        # OR-reduced flag exactly like device_partition_pages
        targets, out_valid, num = compact_indices(flat.valid, out_cap)
        blocks = []
        for blk in flat.blocks:
            if isinstance(blk.data, tuple):
                data = tuple(scatter_column(dd, targets, out_cap)
                             for dd in blk.data)
            else:
                data = scatter_column(blk.data, targets, out_cap)
            nulls = (scatter_column(blk.nulls, targets, out_cap)
                     if blk.nulls is not None else None)
            blocks.append(blk.with_data(data, nulls=nulls))
        out = Page(blocks=tuple(blocks), valid=out_valid)
        overflow = jax.lax.psum(
            (num > out_cap).astype(jnp.int32), "d") > 0
        return out, overflow

    # PROCESS-level cache, not ex._jit_cache: the coordinator builds
    # one executor per query, and a per-executor cache would re-pay
    # the shard_map compile for every query (and every test) hitting
    # the same exchange shape. The program depends on the dicts only
    # through their None-pattern (LUT values are operands), and jit
    # re-traces per page schema on its own — so the key is just the
    # exchange geometry. Benign-race dict like _ICI_MESHES: a lost
    # write costs one duplicate compile, never a wrong program.
    key = (keys, d, out_cap,
           tuple(dct is not None for dct in dicts), nluts)
    if key not in _ICI_PROGRAMS:
        fn = jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=(PS("d"),) + (PS(),) * nluts,
            out_specs=(PS("d"), PS()), check_vma=False,
        ))
        if jax.default_backend() == "cpu":
            # the CPU collective-rendezvous fence, same reasoning as
            # DistExecutor._fenced (dataflow-readiness scheduling can
            # interleave two in-flight collectives)
            inner = fn

            def fn(*args):
                out = inner(*args)
                # xfercheck: raw-ok - sync fence (no copy): pins
                jax.block_until_ready(out)  # rendezvous order on CPU
                return out

        _ICI_PROGRAMS[key] = fn
    return _ICI_PROGRAMS[key]


def ici_exchange_pages(ex, pages, keys: Tuple[int, ...], nparts: int):
    """Exchange spooled raw producer pages into `nparts` partition
    page lists over the device interconnect — ONE all_to_all program
    per raw page, no serialization and no host hop anywhere on the
    path (device-resident inputs stage with zero metered bytes; a
    host-resident input pays its honest h2d once).

    Returns ``(parts, ici_bytes)`` where ``parts[p]`` is the list of
    device partition pages consumer task p reads (capacities identical
    to what `device_partition_pages` would have spooled) and
    ``ici_bytes`` is the static byte footprint routed through the
    collective's send buffers — the ledger row `exec/counters.py`
    declares and `adaptive/replanner.py` costs exchanges with.

    Overflow discipline: the per-program OR-reduced flag settles HERE
    (the coordinator owns this exchange; there is no worker attempt
    loop to defer into) — each overflowing round re-runs EVERY page at
    the next ladder rung so all partition pages land at one capacity,
    counting `capacity_boost_retries` like any other boosted retry."""
    from presto_tpu.dist import spool as SPOOL
    from presto_tpu.exec import shapes as SH
    from presto_tpu.exec.executor import page_bytes

    pages = list(pages)
    if not ici_exchange_supported(nparts, pages):
        raise ValueError(
            f"ici exchange unsupported: nparts={nparts} over "
            f"{len(jax.devices())} devices, caps="
            f"{[p.capacity for p in pages]}")
    d = nparts
    mesh = _ici_mesh(d)
    page_spec = NamedSharding(mesh, PS("d"))
    lut_spec = NamedSharding(mesh, PS())
    ici_bytes = 0
    staged = []
    for page in pages:
        dicts = tuple(page.block(k).dictionary for k in keys)
        luts = tuple(
            XF.to_device(SPOOL._dict_value_hashes(dct), spec=lut_spec,
                         label="dict-hash")
            if dct is not None else None
            for dct in dicts
        )
        pg = XF.to_device(page, spec=page_spec,
                          label="ici-exchange-stage")
        ici_bytes += page_bytes(page)
        staged.append((pg, dicts, luts))
    boost = ex._capacity_boost
    while True:
        outs = []
        overflowed = False
        for pg, dicts, luts in staged:
            out_cap = SH.exchange_partition_cap(
                pg.capacity, nparts, boost)
            fn = _ici_program(ex, mesh, keys, dicts,
                              sum(1 for v in luts if v is not None),
                              d, out_cap)
            out, overflow = fn(pg, *[v for v in luts
                                     if v is not None])
            outs.append((out, out_cap))
            if bool(overflow):
                overflowed = True
        if not overflowed:
            break
        boost = SH.next_boost(boost)
        ex.capacity_boost_retries += 1
        if boost > SH.DEVICE_FAULT_ROWS:
            raise RuntimeError(
                "ici exchange overflow did not settle on the boost "
                "ladder")
    parts: List[List[Page]] = [[] for _ in range(nparts)]
    for out, out_cap in outs:
        # shard p of the exchanged page IS partition p: slice it out
        # as consumer task p's device page (device-side view, no
        # crossing — the spool data plane serves it from here)
        for p in range(nparts):
            parts[p].append(jax.tree.map(
                lambda x, p=p: x[p * out_cap:(p + 1) * out_cap], out))
    return parts, ici_bytes
