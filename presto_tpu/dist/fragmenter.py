"""Exchange insertion: decide each operator's distribution and place
Exchange nodes at the boundaries.

Reference: presto-main sql/planner/optimizations/AddExchanges.java (the
partitioned-vs-broadcast join decision, SINGLE gathers before final
stages) + PlanFragmenter.java (stage cutting). Our stages need no explicit
fragment objects: every Exchange in the tree IS the stage boundary, and
the DistExecutor compiles the collectives directly into the neighboring
kernels.

Distributions (PartitioningHandle analogs):
  "sharded"    — rows split across mesh devices (FIXED/SOURCE distribution)
  "replicated" — every device holds all rows (the degenerate same-everywhere
                 form of SINGLE: gather-to-one with free replication, which
                 is how a SINGLE stage looks when every device runs it)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

from presto_tpu.exec import plan as P

SHARDED = "sharded"
REPLICATED = "replicated"

# build sides up to this many estimated rows replicate to every device
# (reference: join-distribution-type=broadcast + small-table heuristic)
BROADCAST_ROWS = 1 << 21
# grouped aggregations up to this capacity gather partials to one stream;
# larger ones repartition by group key so final state stays sharded
GATHER_CAPACITY = 1 << 17


def est_rows(node: P.PhysicalNode, catalogs) -> int:
    """Crude static cardinality estimate (reference: the pre-CBO era's
    source-size heuristics in DetermineJoinDistributionType)."""
    if isinstance(node, P.TableScan):
        return catalogs[node.catalog].row_count(node.table)
    if isinstance(node, P.Values):
        return len(node.rows)
    if isinstance(node, (P.Filter, P.Project, P.UniqueId, P.Exchange)):
        return est_rows(node.source, catalogs)
    if isinstance(node, P.Aggregation):
        base = est_rows(node.source, catalogs)
        return 1 if not node.group_channels else min(base, node.capacity)
    if isinstance(node, P.HashJoin):
        left = est_rows(node.left, catalogs)
        if node.join_type in ("semi", "anti", "left"):
            return left
        return max(left, est_rows(node.right, catalogs))
    if isinstance(node, P.CrossJoin):
        return est_rows(node.left, catalogs) * max(
            est_rows(node.right, catalogs), 1
        )
    if isinstance(node, P.Union):
        return sum(est_rows(s, catalogs) for s in node.sources)
    if isinstance(node, (P.Sort, P.Output, P.Window, P.MarkDistinct)):
        return est_rows(node.source, catalogs)
    if isinstance(node, P.GroupId):
        return est_rows(node.source, catalogs) * len(node.set_masks)
    if isinstance(node, P.Unnest):
        return est_rows(node.source, catalogs) * 4
    if isinstance(node, P.TopN):
        return min(est_rows(node.source, catalogs), node.limit)
    if isinstance(node, P.Limit):
        return min(est_rows(node.source, catalogs),
                   node.count + node.offset)
    return 1 << 30


def _gather(node):
    return P.Exchange(source=node, kind="gather")


def add_exchanges(
    node: P.PhysicalNode,
    catalogs,
    *,
    broadcast_rows: int = BROADCAST_ROWS,
    gather_capacity: int = GATHER_CAPACITY,
    broadcast_bytes: Optional[int] = None,
    row_bytes_of: Optional[Callable[[P.PhysicalNode], int]] = None,
) -> Tuple[P.PhysicalNode, str]:
    """Rewrite a single-stream physical plan into a distributed one.

    Returns (plan', distribution of its output). The root is always
    gathered so Output decodes a replicated page.

    Broadcast-vs-partitioned: with `broadcast_bytes` + `row_bytes_of`
    supplied (runner wires them from exact connector row counts and the
    per-chip memory-governor share, membudget.py), the decision is
    STATS-DRIVEN — a build side replicates only when its estimated
    byte footprint fits one chip's broadcast share — replacing the
    fixed `broadcast_rows` threshold (reference: the table-stats path
    of DetermineJoinDistributionType vs its row-count fallback)."""

    def build_broadcasts(n_right) -> bool:
        rows = est_rows(n_right, catalogs)
        if broadcast_bytes is not None and row_bytes_of is not None:
            # byte-governed, but a replicated build is still ONE device
            # buffer — it must also stay under the per-buffer row
            # ceiling (shapes.SAFE_BUFFER_ROWS, the axon fault line
            # with headroom) that the fixed row threshold used to
            # enforce implicitly; a narrow-but-long build that fits the
            # byte share would otherwise all_gather past the line
            from presto_tpu.exec import shapes as SH

            return (rows <= SH.SAFE_BUFFER_ROWS
                    and rows * row_bytes_of(n_right) <= broadcast_bytes)
        return rows <= broadcast_rows

    def rewrite(n) -> Tuple[P.PhysicalNode, str]:
        if isinstance(n, P.TableScan):
            return n, SHARDED
        if isinstance(n, P.Values):
            return n, REPLICATED
        if isinstance(
            n, (P.Filter, P.Project, P.UniqueId, P.GroupId, P.Unnest)
        ):
            # row-local transforms keep their source's distribution
            # (GroupId replicas and Unnest expansion are per-row,
            # shard-transparent)
            src, d = rewrite(n.source)
            return dataclasses.replace(n, source=src), d
        if isinstance(n, P.Union):
            parts = [rewrite(s) for s in n.sources]
            if all(d == REPLICATED for _, d in parts):
                return P.Union(tuple(s for s, _ in parts)), REPLICATED
            # mixed or all-sharded: bring everything to sharded? a
            # replicated branch concatenated into a sharded stream would
            # duplicate rows per device — gather the sharded branches
            # instead (correct for the small unions the planner emits)
            srcs = tuple(
                s if d == REPLICATED else _gather(s) for s, d in parts
            )
            return P.Union(srcs), REPLICATED
        if isinstance(n, P.Aggregation):
            src, d = rewrite(n.source)
            if d == REPLICATED:
                return dataclasses.replace(n, source=src), REPLICATED
            partial = dataclasses.replace(n, source=src, step="partial")
            nkeys = len(n.group_channels)
            if not nkeys or n.capacity <= gather_capacity:
                ex = _gather(partial)
                out_d = REPLICATED
            else:
                ex = P.Exchange(
                    source=partial, kind="repartition",
                    keys=tuple(range(nkeys)),
                )
                out_d = SHARDED
            final = dataclasses.replace(
                n, source=ex, step="final",
                group_channels=tuple(range(nkeys)),
            )
            return final, out_d
        if isinstance(n, P.HashJoin):
            left, dl = rewrite(n.left)
            right, dr = rewrite(n.right)
            if dl == REPLICATED and dr == REPLICATED:
                return dataclasses.replace(
                    n, left=left, right=right), REPLICATED
            if dr == SHARDED:
                if build_broadcasts(n.right):
                    right = P.Exchange(source=right, kind="broadcast")
                    dr = REPLICATED
                elif dl == REPLICATED:
                    right = _gather(right)
                    dr = REPLICATED
                else:
                    # partitioned join: both sides repartition on the
                    # equi-join keys so matching rows co-locate
                    left = P.Exchange(
                        source=left, kind="repartition",
                        keys=n.left_keys,
                    )
                    right = P.Exchange(
                        source=right, kind="repartition",
                        keys=n.right_keys,
                    )
                    return dataclasses.replace(
                        n, left=left, right=right), SHARDED
            # dr now REPLICATED; output follows probe side
            return dataclasses.replace(n, left=left, right=right), dl
        if isinstance(n, P.CrossJoin):
            left, dl = rewrite(n.left)
            right, dr = rewrite(n.right)
            if dl == SHARDED and est_rows(n.left, catalogs) > 0:
                # keep probe sharded, replicate the (small) build side
                if dr == SHARDED:
                    right = P.Exchange(source=right, kind="broadcast")
                return P.CrossJoin(left, right), SHARDED
            if dr == SHARDED:
                right = _gather(right)
            return P.CrossJoin(left, right), dl
        if isinstance(n, (P.Sort, P.TopN, P.Limit, P.Output, P.Window,
                          P.MarkDistinct)):
            # MarkDistinct needs a global view of each key set (first-
            # occurrence marks are meaningless per shard) — conservative
            # gather, like Sort/Window (reference: MarkDistinctNode
            # forces its own exchange too)
            src, d = rewrite(n.source)
            if d == SHARDED:
                src = _gather(src)
            return dataclasses.replace(n, source=src), REPLICATED
        raise TypeError(f"add_exchanges: unknown node {n!r}")

    return rewrite(node)
