"""Exchange insertion: decide each operator's distribution and place
Exchange nodes at the boundaries.

Reference: presto-main sql/planner/optimizations/AddExchanges.java (the
partitioned-vs-broadcast join decision, SINGLE gathers before final
stages) + PlanFragmenter.java (stage cutting). For the in-mesh
DistExecutor our stages need no explicit fragment objects: every
Exchange in the tree IS the stage boundary, and the collectives compile
directly into the neighboring kernels.

For the DCN (multi-process) layer, `fragment_dag` below goes the other
half of PlanFragmenter.java: it CUTS the exchanged tree at every
Exchange into an explicit DAG of plan fragments (stages) connected by
gather / broadcast / hash-repartition edges, which dist/scheduler.py
walks in dependency order and dispatches task-by-task across the
worker pool — the general multi-stage shape PAPER.md §1 prescribes,
replacing the three special-cased cuts (agg-cut, union-cut,
hash-fanout-join) for every plan they cannot express.

Distributions (PartitioningHandle analogs):
  "sharded"    — rows split across mesh devices (FIXED/SOURCE distribution)
  "replicated" — every device holds all rows (the degenerate same-everywhere
                 form of SINGLE: gather-to-one with free replication, which
                 is how a SINGLE stage looks when every device runs it)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from presto_tpu.exec import plan as P

SHARDED = "sharded"
REPLICATED = "replicated"

# build sides up to this many estimated rows replicate to every device
# (reference: join-distribution-type=broadcast + small-table heuristic)
BROADCAST_ROWS = 1 << 21
# grouped aggregations up to this capacity gather partials to one stream;
# larger ones repartition by group key so final state stays sharded
GATHER_CAPACITY = 1 << 17


def est_rows(node: P.PhysicalNode, catalogs) -> int:
    """Crude static cardinality estimate (reference: the pre-CBO era's
    source-size heuristics in DetermineJoinDistributionType)."""
    if isinstance(node, P.TableScan):
        return catalogs[node.catalog].row_count(node.table)
    if isinstance(node, P.Values):
        return len(node.rows)
    if isinstance(node, (P.Filter, P.Project, P.UniqueId, P.Exchange)):
        return est_rows(node.source, catalogs)
    if isinstance(node, P.Aggregation):
        base = est_rows(node.source, catalogs)
        return 1 if not node.group_channels else min(base, node.capacity)
    if isinstance(node, P.HashJoin):
        left = est_rows(node.left, catalogs)
        if node.join_type in ("semi", "anti", "left"):
            return left
        return max(left, est_rows(node.right, catalogs))
    if isinstance(node, P.CrossJoin):
        return est_rows(node.left, catalogs) * max(
            est_rows(node.right, catalogs), 1
        )
    if isinstance(node, P.Union):
        return sum(est_rows(s, catalogs) for s in node.sources)
    if isinstance(node, (P.Sort, P.Output, P.Window, P.MarkDistinct)):
        return est_rows(node.source, catalogs)
    if isinstance(node, P.GroupId):
        return est_rows(node.source, catalogs) * len(node.set_masks)
    if isinstance(node, P.Unnest):
        return est_rows(node.source, catalogs) * 4
    if isinstance(node, P.TopN):
        return min(est_rows(node.source, catalogs), node.limit)
    if isinstance(node, P.Limit):
        return min(est_rows(node.source, catalogs),
                   node.count + node.offset)
    return 1 << 30


def _gather(node):
    return P.Exchange(source=node, kind="gather")


def add_exchanges(
    node: P.PhysicalNode,
    catalogs,
    *,
    broadcast_rows: int = BROADCAST_ROWS,
    gather_capacity: int = GATHER_CAPACITY,
    broadcast_bytes: Optional[int] = None,
    row_bytes_of: Optional[Callable[[P.PhysicalNode], int]] = None,
) -> Tuple[P.PhysicalNode, str]:
    """Rewrite a single-stream physical plan into a distributed one.

    Returns (plan', distribution of its output). The root is always
    gathered so Output decodes a replicated page.

    Broadcast-vs-partitioned: with `broadcast_bytes` + `row_bytes_of`
    supplied (runner wires them from exact connector row counts and the
    per-chip memory-governor share, membudget.py), the decision is
    STATS-DRIVEN — a build side replicates only when its estimated
    byte footprint fits one chip's broadcast share — replacing the
    fixed `broadcast_rows` threshold (reference: the table-stats path
    of DetermineJoinDistributionType vs its row-count fallback)."""

    def build_broadcasts(n_right) -> bool:
        rows = est_rows(n_right, catalogs)
        if broadcast_bytes is not None and row_bytes_of is not None:
            # byte-governed, but a replicated build is still ONE device
            # buffer — it must also stay under the per-buffer row
            # ceiling (shapes.SAFE_BUFFER_ROWS, the axon fault line
            # with headroom) that the fixed row threshold used to
            # enforce implicitly; a narrow-but-long build that fits the
            # byte share would otherwise all_gather past the line
            from presto_tpu.exec import shapes as SH

            return (rows <= SH.SAFE_BUFFER_ROWS
                    and rows * row_bytes_of(n_right) <= broadcast_bytes)
        return rows <= broadcast_rows

    def rewrite(n) -> Tuple[P.PhysicalNode, str]:
        if isinstance(n, P.TableScan):
            return n, SHARDED
        if isinstance(n, P.Values):
            return n, REPLICATED
        if isinstance(
            n, (P.Filter, P.Project, P.UniqueId, P.GroupId, P.Unnest)
        ):
            # row-local transforms keep their source's distribution
            # (GroupId replicas and Unnest expansion are per-row,
            # shard-transparent)
            src, d = rewrite(n.source)
            return dataclasses.replace(n, source=src), d
        if isinstance(n, P.Union):
            parts = [rewrite(s) for s in n.sources]
            if all(d == REPLICATED for _, d in parts):
                return P.Union(tuple(s for s, _ in parts)), REPLICATED
            # mixed or all-sharded: bring everything to sharded? a
            # replicated branch concatenated into a sharded stream would
            # duplicate rows per device — gather the sharded branches
            # instead (correct for the small unions the planner emits)
            srcs = tuple(
                s if d == REPLICATED else _gather(s) for s, d in parts
            )
            return P.Union(srcs), REPLICATED
        if isinstance(n, P.Aggregation):
            src, d = rewrite(n.source)
            if d == REPLICATED:
                return dataclasses.replace(n, source=src), REPLICATED
            partial = dataclasses.replace(n, source=src, step="partial")
            nkeys = len(n.group_channels)
            if not nkeys or n.capacity <= gather_capacity:
                ex = _gather(partial)
                out_d = REPLICATED
            else:
                ex = P.Exchange(
                    source=partial, kind="repartition",
                    keys=tuple(range(nkeys)),
                )
                out_d = SHARDED
            final = dataclasses.replace(
                n, source=ex, step="final",
                group_channels=tuple(range(nkeys)),
            )
            return final, out_d
        if isinstance(n, P.HashJoin):
            left, dl = rewrite(n.left)
            right, dr = rewrite(n.right)
            if dl == REPLICATED and dr == REPLICATED:
                return dataclasses.replace(
                    n, left=left, right=right), REPLICATED
            if dr == SHARDED:
                if build_broadcasts(n.right):
                    right = P.Exchange(source=right, kind="broadcast")
                    dr = REPLICATED
                elif dl == REPLICATED:
                    right = _gather(right)
                    dr = REPLICATED
                else:
                    # partitioned join: both sides repartition on the
                    # equi-join keys so matching rows co-locate
                    left = P.Exchange(
                        source=left, kind="repartition",
                        keys=n.left_keys,
                    )
                    right = P.Exchange(
                        source=right, kind="repartition",
                        keys=n.right_keys,
                    )
                    return dataclasses.replace(
                        n, left=left, right=right), SHARDED
            # dr now REPLICATED; output follows probe side
            return dataclasses.replace(n, left=left, right=right), dl
        if isinstance(n, P.CrossJoin):
            left, dl = rewrite(n.left)
            right, dr = rewrite(n.right)
            if dl == SHARDED and est_rows(n.left, catalogs) > 0:
                # keep probe sharded, replicate the (small) build side
                if dr == SHARDED:
                    right = P.Exchange(source=right, kind="broadcast")
                return P.CrossJoin(left, right), SHARDED
            if dr == SHARDED:
                right = _gather(right)
            return P.CrossJoin(left, right), dl
        if isinstance(n, (P.Sort, P.TopN, P.Limit, P.Output, P.Window,
                          P.MarkDistinct)):
            # MarkDistinct needs a global view of each key set (first-
            # occurrence marks are meaningless per shard) — conservative
            # gather, like Sort/Window (reference: MarkDistinctNode
            # forces its own exchange too)
            src, d = rewrite(n.source)
            if d == SHARDED:
                src = _gather(src)
            return dataclasses.replace(n, source=src), REPLICATED
        raise TypeError(f"add_exchanges: unknown node {n!r}")

    return rewrite(node)


# ---------------------------------------------------------------------
# Stage-DAG fragmentation (the DCN half of PlanFragmenter.java): cut an
# exchanged plan into explicit fragments for task-by-task scheduling.

@dataclasses.dataclass(frozen=True)
class Fragment:
    """One stage of a DCN stage DAG.

    root: the fragment's plan subtree; its RemoteSource leaves
        (key="stage<fid>") reference upstream fragments and carry the
        producer root as `origin`, so plan_check can verify the whole
        multi-hop edge chain.
    inputs: upstream fragment ids this fragment consumes.
    output_kind: how the consumer ingests this fragment's output —
        "gather"/"broadcast" consumers read every producer task's whole
        spool; "repartition" producers spool P hash partitions and
        consumer task t reads partition t of every producer task;
        "passthrough" (adaptive-only, ISSUE 15: the degrade of a
        repartition producer under a broadcast-flipped join) spools
        ONE partition per task and consumer task t reads producer
        task t's whole spool — a disjoint split with no hashing.
    output_keys: partition channels for a repartition edge.
    sharded: run one task per pooled worker (leaf scans split
        round-robin on split_table; repartition consumers read their
        partition); un-sharded fragments run as ONE task.
    split_table: the fact table split across a sharded leaf fragment's
        tasks (largest scanned table, SOURCE_DISTRIBUTION pick).
    """

    fid: int
    root: P.PhysicalNode
    inputs: Tuple[int, ...]
    output_kind: str
    output_keys: Tuple[int, ...] = ()
    sharded: bool = True
    split_table: Optional[str] = None


@dataclasses.dataclass
class StageDag:
    """Topologically ordered fragments plus the coordinator-side root
    plan (RemoteSource leaves referencing the final fragments).

    The two adaptive-execution fields (ISSUE 15) start empty and are
    written only by presto_tpu/adaptive/ between stage dispatches:

    reads: (consumer_fid, producer_fid) -> "broadcast" overrides HOW a
        consumer ingests an edge whose producer ALREADY spooled — a
        repartition spool read broadcast-style drains every partition
        of every producer task (their union is the full output), the
        runtime half of a partitioned->broadcast distribution flip.
        -1 as consumer_fid addresses the coordinator root fragment.
    hints: fid -> payload hints for not-yet-dispatched fragments
        (currently {"skew": True} pre-engages the position-chunked
        join rebalance on the consumer of a skewed exchange).
    """

    fragments: List[Fragment]
    root: P.PhysicalNode
    root_inputs: Tuple[int, ...]
    reads: Dict[Tuple[int, int], str] = dataclasses.field(
        default_factory=dict)
    hints: Dict[int, Dict] = dataclasses.field(default_factory=dict)

    def fragment(self, fid: int) -> Fragment:
        return self.fragments[fid]

    def consumers(self, fid: int) -> List[int]:
        return [f.fid for f in self.fragments if fid in f.inputs]

    def read_kind(self, consumer_fid: int, producer_fid: int) -> str:
        """Effective ingest mode of one edge: the producer's spooled
        output_kind unless an adaptive read override redirects it."""
        override = self.reads.get((consumer_fid, producer_fid))
        return override or self.fragments[producer_fid].output_kind


def stage_key(fid: int) -> str:
    """The RemoteSource registry key of fragment fid — stable across
    queries so jit-cache keys derived from plan content stay canonical
    (a per-query key would mint fresh program shapes per query)."""
    return f"stage{fid}"


def _map_children(n: P.PhysicalNode, fn) -> P.PhysicalNode:
    """Rebuild one node with ``fn`` applied to every child field
    (direct PhysicalNode fields and tuples of them) — THE shared
    structural-rewrite step for cut()/clip_for_shipping, so a future
    child-field shape cannot be handled by one traversal and silently
    skipped by the other."""
    changes = {}
    for f in dataclasses.fields(n):
        v = getattr(n, f.name)
        if isinstance(v, P.PhysicalNode):
            nv = fn(v)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and v and isinstance(
            v[0], P.PhysicalNode
        ):
            nv = tuple(fn(x) for x in v)
            if any(a is not b for a, b in zip(nv, v)):
                changes[f.name] = nv
    return dataclasses.replace(n, **changes) if changes else n


def clip_for_shipping(n: P.PhysicalNode) -> P.PhysicalNode:
    """Bound a shipped fragment's payload: RemoteSource.origin carries
    the producer's whole subtree (which itself nests ITS producers'
    origins), so serializing fragment roots verbatim grows task
    payloads ~O(stages^2) down a chain — and the blob re-ships on
    every retry and speculation copy. Workers only need origins where
    TYPE RESOLUTION does (a final-step Aggregation recovers its
    partial's input types through its source's origin); keep exactly
    those chains, clipped recursively, and drop the rest
    (estimate_rows degrades to its floor on the worker; the
    coordinator-side StageDag keeps full origins for verify_dag)."""
    if isinstance(n, P.Aggregation) and n.step == "final" and \
            isinstance(n.source, P.RemoteSource) and \
            n.source.origin is not None:
        return dataclasses.replace(n, source=dataclasses.replace(
            n.source, origin=clip_for_shipping(n.source.origin)))
    if isinstance(n, P.RemoteSource):
        if n.origin is None:
            return n
        return dataclasses.replace(n, origin=None)
    return _map_children(n, clip_for_shipping)


def _keys_repartitionable(types, keys) -> bool:
    """Whether an inter-task hash-repartition on these channels is
    sound. Dictionary codes are table-local (two producer tasks encode
    the same string with different codes), so string/dictionary keys
    cannot hash consistently across tasks — the same rule as the
    executor's _keys_partitionable and the hash-fanout analyzer."""
    from presto_tpu import types as T

    for k in keys:
        t = types[k]
        if T.is_string(t) or t.is_dictionary_encoded:
            return False
    return True


def _has_scan(n: P.PhysicalNode) -> bool:
    if isinstance(n, P.TableScan):
        return True
    return any(_has_scan(c) for c in n.children())


def _has_work(n: P.PhysicalNode) -> bool:
    """Worth shipping: generation alone is cheaper than the wire (the
    same rule as find_union_cut) — a fragment must filter, join, or
    aggregate to be worth a task."""
    if isinstance(n, (P.HashJoin, P.CrossJoin, P.Filter, P.Aggregation,
                      P.Window, P.Sort, P.TopN, P.MarkDistinct)):
        return True
    return any(_has_work(c) for c in n.children())


def _dag_safe(n: P.PhysicalNode) -> bool:
    """Shapes the stage DAG must refuse (fall back to the legacy cuts /
    local execution rather than run wrong):

    - right/full outer joins whose build side REPLICATES while the
      probe side is sharded: every task would emit the globally
      unmatched build rows, duplicating them per task (co-partitioned
      right/full joins are fine — each build row lives in exactly one
      partition);
    - UniqueId under a sharded subtree: per-task counters would mint
      colliding "unique" ids across tasks.
    """
    if isinstance(n, P.UniqueId):
        return False
    if isinstance(n, P.HashJoin) and n.join_type in ("right", "full"):
        right_broadcast = (
            isinstance(n.right, P.Exchange)
            and n.right.kind == "broadcast"
        ) or not _has_scan_or_repart(n.right)
        if right_broadcast and _has_scan_or_repart(n.left):
            return False
    return all(_dag_safe(c) for c in n.children())


def _has_scan_or_repart(n: P.PhysicalNode) -> bool:
    """Whether a subtree of the EXCHANGED plan is sharded: it scans a
    table (scans shard round-robin) or sits under a repartition
    exchange boundary."""
    if isinstance(n, P.TableScan):
        return True
    if isinstance(n, P.Exchange):
        if n.kind == "repartition":
            return True
        return False  # gather/broadcast boundaries replicate upward
    if isinstance(n, P.RemoteSource):
        return False
    return any(_has_scan_or_repart(c) for c in n.children())


def fragment_dag(
    ex,
    plan: P.PhysicalNode,
    catalogs,
    *,
    broadcast_rows: int = BROADCAST_ROWS,
    gather_capacity: int = GATHER_CAPACITY,
    broadcast_bytes: Optional[int] = None,
    row_bytes_of: Optional[Callable[[P.PhysicalNode], int]] = None,
) -> Optional[StageDag]:
    """Cut ANY single-stream physical plan into a stage DAG.

    Runs add_exchanges (the same stats-driven broadcast-vs-partitioned
    and gather-vs-repartition decisions the in-mesh executor uses),
    then cuts the tree at every Exchange: the subtree below becomes a
    Fragment and the consumer sees a RemoteSource whose declared types
    are the producer's output schema and whose `origin` carries the
    producer root (the verifiable fragment edge). Returns None when the
    plan is not worth distributing (no joining/filtering/aggregating
    fragment) or not DAG-safe (see _dag_safe) — callers fall back to
    the legacy cuts or local execution.

    `ex` is an Executor used only for schema resolution
    (ex.output_types); nothing traces or compiles here.
    """
    # lazy: server.worker imports dist.serde, so a module-level import
    # here would cycle through dist/__init__
    from presto_tpu.server.worker import largest_table

    exd, _dist = add_exchanges(
        plan, catalogs, broadcast_rows=broadcast_rows,
        gather_capacity=gather_capacity,
        broadcast_bytes=broadcast_bytes, row_bytes_of=row_bytes_of,
    )
    if not _dag_safe(exd):
        return None
    frags: List[Fragment] = []

    def collect_inputs(n) -> Tuple[int, ...]:
        out: List[int] = []

        def walk(x):
            if isinstance(x, P.RemoteSource):
                if x.key.startswith("stage"):
                    out.append(int(x.key[len("stage"):]))
                return  # origins are metadata, not edges
            for c in x.children():
                walk(c)

        walk(n)
        return tuple(dict.fromkeys(out))

    def cut(n: P.PhysicalNode) -> P.PhysicalNode:
        if isinstance(n, P.Exchange):
            src = cut(n.source)
            kind, keys = n.kind, tuple(n.keys)
            if kind == "repartition" and not _keys_repartitionable(
                ex.output_types(src), keys
            ):
                # dictionary-coded partition keys cannot hash
                # consistently across producer tasks — degrade the
                # edge to a gather (single consumer task). Both sides
                # of a co-partitioned join degrade symmetrically: the
                # verifier pins equal type families on join key pairs.
                kind, keys = "gather", ()
            inputs = collect_inputs(src)
            sharded = _has_scan(src) or any(
                frags[i].output_kind == "repartition" for i in inputs
            )
            split_table = (
                largest_table(src, catalogs) if _has_scan(src) else None
            )
            fid = len(frags)
            frags.append(Fragment(
                fid=fid, root=src, inputs=inputs, output_kind=kind,
                output_keys=keys, sharded=sharded,
                split_table=split_table,
            ))
            return P.RemoteSource(
                types=tuple(ex.output_types(src)), key=stage_key(fid),
                origin=src,
            )
        return _map_children(n, cut)

    root = cut(exd)
    if not frags:
        return None
    if not any(_has_work(f.root) for f in frags):
        return None  # bare scans: generation is cheaper than the wire

    # post-cut safety re-check: the dictionary-key degrade above can
    # turn a repartition edge into a gather AFTER _dag_safe ran on the
    # exchanged tree — if that re-creates a replicated-build right/full
    # join inside a SHARDED fragment (every task would emit the
    # globally-unmatched build rows), refuse the DAG outright
    def _side_sharded(n) -> bool:
        if isinstance(n, P.TableScan):
            return True
        if isinstance(n, P.RemoteSource) and n.key.startswith("stage"):
            fid = int(n.key[len("stage"):])
            return frags[fid].output_kind == "repartition"
        return any(_side_sharded(c) for c in n.children())

    def _cut_safe(n) -> bool:
        if isinstance(n, P.HashJoin) and \
                n.join_type in ("right", "full") and \
                _side_sharded(n.left) and not _side_sharded(n.right):
            return False
        return all(_cut_safe(c) for c in n.children())

    if not all(_cut_safe(f.root) for f in frags if f.sharded):
        return None
    return StageDag(fragments=frags, root=root,
                    root_inputs=collect_inputs(root))
