"""Keep-alive HTTP connection pool for the shuffle plane (ISSUE 16).

Reference: presto-main operator/HttpPageBufferClient rides an async
HTTP client with pooled keep-alive connections; our DCN plane opened
a fresh TCP connection per request (urlopen) for every page fetch,
status poll, ack, and release. This module gives `dist/dcn.py`,
`dist/spool.py`, and `dist/scheduler.py` one shared per-destination
pool with urlopen-compatible semantics:

  - `request(url, ...)` returns a response object with `.status`,
    `.headers`, `.read(n)`, usable as a context manager — and raises
    `urllib.error.HTTPError` on >= 400 (with `.code`/`.headers`/
    `.read()` intact) and `urllib.error.URLError` on transport
    failure, so every existing except-clause and retry ladder on the
    fetch plane (PR-5/7 recovery semantics) behaves exactly as it
    did with urlopen.
  - Lock discipline (tools/concheck.py): the pool lock guards ONLY
    the free-list take/put and the reuse tallies. Connects, sends,
    reads, and closes all happen outside it.
  - Loud fallback: a request that fails on a REUSED connection (the
    peer closed a keep-alive socket between requests) retries once
    on a fresh connection and counts/logs the failover — never a
    silent extra retry burned from the caller's bounded ladder.
    POSTs never ride a reused connection at all: a replayed task
    submit on a half-dead socket could double-create a task, and
    submits are rare next to fetches.

Reused-connection requests are metered onto the thread-bound
transfer sink's `exchange_fetch_reused_conns` registry counter
(exec/counters.py) plus module process totals for /metrics.
"""

from __future__ import annotations

import http.client
import io
import logging
import urllib.error
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from presto_tpu.exec import xfer as XF
from presto_tpu.obs.sanitizer import make_lock, register_owner

_LOG = logging.getLogger("presto_tpu.dist.connpool")

# process-lifetime totals (the dist/serde.py `_TOTALS` pattern)
_TOTALS = {"exchange_fetch_reused_conns": 0, "exchange_pool_failovers": 0}

# bound the response bytes close() will drain to recycle a
# connection; anything larger just closes the socket
_DRAIN_LIMIT = 1 << 16


class _PooledResponse:
    """One in-flight response bound to its pooled connection. Reading
    to EOF (or closing with only a small remainder) returns the
    connection to the pool; anything irregular closes it."""

    def __init__(self, pool: "ConnectionPool", key, conn, resp):
        self._pool = pool
        self._key = key
        self._conn = conn
        self._resp = resp
        self._released = False

    @property
    def status(self) -> int:
        return self._resp.status

    @property
    def headers(self):
        return self._resp.headers

    def read(self, amt: Optional[int] = None) -> bytes:
        return self._resp.read() if amt is None else self._resp.read(amt)

    def close(self) -> None:
        if self._released:
            return
        self._released = True
        reusable = False
        try:
            if not self._resp.isclosed():
                left = self._resp.length
                if left is not None and left <= _DRAIN_LIMIT:
                    self._resp.read()
            reusable = self._resp.isclosed() and not self._resp.will_close
        except (OSError, http.client.HTTPException):
            reusable = False
        if reusable:
            self._pool._give(self._key, self._conn)
        else:
            self._conn.close()

    def __enter__(self) -> "_PooledResponse":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ConnectionPool:
    """Per-destination keep-alive connection free-lists."""

    # tally rebinds happen under _lock (obs/sanitizer.py owner check)
    _shared_attrs = ("reused_total", "failover_total")

    def __init__(self, max_per_dest: int = 4):
        self.max_per_dest = max_per_dest
        self._conns: Dict[Tuple[str, str], List] = {}
        self.reused_total = 0
        self.failover_total = 0
        self._lock = make_lock("dist.connpool.ConnectionPool._lock")
        register_owner(self, lock_attrs=("_lock",))

    # ------------------------------------------------------ free list
    def _take(self, key):
        with self._lock:
            lst = self._conns.get(key)
            if lst:
                return lst.pop()
        return None

    def _give(self, key, conn) -> None:
        with self._lock:
            lst = self._conns.setdefault(key, [])
            if len(lst) < self.max_per_dest:
                lst.append(conn)
                return
        conn.close()  # over cap: closed OUTSIDE the lock

    def _count_reuse(self) -> None:
        with self._lock:
            self.reused_total += 1
        _TOTALS["exchange_fetch_reused_conns"] += 1
        sink = XF.current_sink()
        count = getattr(sink, "count_reused_conn", None)
        if count is not None:
            count()

    def _count_failover(self, key, err) -> None:
        with self._lock:
            self.failover_total += 1
        _TOTALS["exchange_pool_failovers"] += 1
        _LOG.warning(
            "pooled connection to %s://%s failed (%s); retrying once "
            "on a fresh connection", key[0], key[1], err)

    # -------------------------------------------------------- request
    def request(self, url: str, *, method: str = "GET",
                data: Optional[bytes] = None, headers=(),
                timeout: float = 60.0) -> _PooledResponse:
        split = urlsplit(url)
        key = (split.scheme or "http", split.netloc)
        path = split.path or "/"
        if split.query:
            path += "?" + split.query
        hdrs = dict(headers)
        # a replayed POST on a half-dead keep-alive socket could
        # reach the server twice — submits always open fresh
        conn = self._take(key) if data is None else None
        reused = conn is not None
        while True:
            fresh = conn is None
            if fresh:
                cls = (http.client.HTTPSConnection
                       if key[0] == "https" else http.client.HTTPConnection)
                conn = cls(key[1], timeout=timeout)
            try:
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
                else:
                    conn.timeout = timeout
                conn.request(method, path, body=data, headers=hdrs)
                resp = conn.getresponse()
                break
            except (http.client.HTTPException, ConnectionError,
                    OSError) as e:
                conn.close()
                conn = None
                if reused and fresh is False:
                    # loud fallback: stale keep-alive, not a peer
                    # failure — retry once without burning one of the
                    # caller's bounded transport retries
                    self._count_failover(key, e)
                    reused = False
                    continue
                raise urllib.error.URLError(e) from e
        if reused:
            self._count_reuse()
        if resp.status >= 400:
            # urlopen contract: error statuses raise, with code/
            # headers/body intact for X-Task-Error and 410 handling
            try:
                body = resp.read()
                reusable = resp.isclosed() and not resp.will_close
            except (OSError, http.client.HTTPException):
                body, reusable = b"", False
            if reusable:
                self._give(key, conn)
            else:
                conn.close()
            raise urllib.error.HTTPError(
                url, resp.status, resp.reason, resp.headers,
                io.BytesIO(body))
        return _PooledResponse(self, key, conn, resp)

    def close_all(self) -> None:
        with self._lock:
            doomed = [c for lst in self._conns.values() for c in lst]
            self._conns.clear()
        for c in doomed:  # socket closes OUTSIDE the lock
            c.close()


_POOL = ConnectionPool()


def request(url: str, *, method: str = "GET",
            data: Optional[bytes] = None, headers=(),
            timeout: float = 60.0) -> _PooledResponse:
    """Issue one HTTP request through THE process-shared pool."""
    return _POOL.request(url, method=method, data=data,
                         headers=headers, timeout=timeout)


def pool_totals() -> dict:
    """Process-lifetime reuse/failover totals, for the /metrics
    overlay and loadbench deltas."""
    return dict(_TOTALS)


def reset_pool() -> None:
    """Close every idle pooled connection (tests, shutdown)."""
    _POOL.close_all()
