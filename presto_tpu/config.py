"""Deployment config: the reference's ``etc/`` layout.

Reference: presto-server's config tiers (SURVEY §6.6) —
``etc/config.properties`` (node/service keys, airlift @Config binding)
and ``etc/catalog/<name>.properties`` (one file per catalog; the
``connector.name`` key selects a ConnectorFactory, remaining keys are
connector-specific). Ours parses the same shapes into engine objects so
a reference-style deployment directory drives the server unchanged:

    etc/config.properties        http-server.http.port=8080
                                 query.max-memory-bytes=268435456
    etc/catalog/tpch.properties  connector.name=tpch
                                 tpch.scale-factor=1.0

Unknown connector names or malformed files raise at load (reference:
unknown config keys are a startup error).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional


def parse_properties(path: str) -> Dict[str, str]:
    """Java-style .properties subset: key=value lines, #/! comments,
    whitespace trimmed (reference: airlift loads these via
    java.util.Properties)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith(("#", "!")):
                continue
            if "=" not in line:
                raise ValueError(
                    f"{path}:{lineno}: expected key=value, got {line!r}"
                )
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


# connector.name -> factory(props) -> Connector (reference:
# ConnectorFactory registry in ConnectorManager; plugins extend it via
# register_connector_factory)
_FACTORIES: Dict[str, Callable] = {}


def register_connector_factory(name: str, factory: Callable) -> None:
    _FACTORIES[name] = factory


def _builtin_factories() -> Dict[str, Callable]:
    def tpch(props):
        from presto_tpu.connectors.tpch import TpchConnector

        return TpchConnector(
            scale=float(props.get("tpch.scale-factor", "0.01"))
        )

    def tpcds(props):
        from presto_tpu.connectors.tpcds import TpcdsConnector

        return TpcdsConnector(
            scale=float(props.get("tpcds.scale-factor", "0.01"))
        )

    def memory(props):
        from presto_tpu.connectors.memory import MemoryConnector

        return MemoryConnector()

    def blackhole(props):
        from presto_tpu.connectors.blackhole import BlackholeConnector

        return BlackholeConnector()

    return {"tpch": tpch, "tpcds": tpcds, "memory": memory,
            "blackhole": blackhole}


def load_catalogs(etc_dir: str) -> Dict[str, object]:
    """Build the catalog map from etc/catalog/*.properties (reference:
    StaticCatalogStore scanning the catalog config dir)."""
    catalog_dir = os.path.join(etc_dir, "catalog")
    factories = dict(_builtin_factories())
    factories.update(_FACTORIES)
    catalogs: Dict[str, object] = {}
    if not os.path.isdir(catalog_dir):
        return catalogs
    for fname in sorted(os.listdir(catalog_dir)):
        if not fname.endswith(".properties"):
            continue
        name = fname[: -len(".properties")]
        props = parse_properties(os.path.join(catalog_dir, fname))
        cname = props.get("connector.name")
        if not cname:
            raise ValueError(
                f"{fname}: missing required key connector.name"
            )
        factory = factories.get(cname)
        if factory is None:
            raise ValueError(
                f"{fname}: unknown connector.name {cname!r} "
                f"(known: {sorted(factories)})"
            )
        catalogs[name] = factory(props)
    return catalogs


def load_node_config(etc_dir: str) -> Dict[str, str]:
    """etc/config.properties, empty when absent (reference: the node/
    service tier; keys consumed by serve_from_etc below)."""
    path = os.path.join(etc_dir, "config.properties")
    if not os.path.exists(path):
        return {}
    return parse_properties(path)


def server_from_etc(etc_dir: str, port: Optional[int] = None, **kw):
    """A PrestoTpuServer wired entirely from an etc/ directory —
    the reference's deployment story (bin/launcher reads etc/)."""
    from presto_tpu.server.http_server import PrestoTpuServer

    conf = load_node_config(etc_dir)
    catalogs = load_catalogs(etc_dir)
    if not catalogs:
        raise ValueError(
            f"no catalogs found under {etc_dir}/catalog/*.properties"
        )
    if port is None:
        port = int(conf.get("http-server.http.port", "8080"))
    mem = int(conf.get("query.max-memory-bytes", "0")) or None
    # persistent compile cache (reference analog: compiled-artifact
    # reuse across queries): one dir per machine outlives every server
    # process pointed at it
    cache_dir = conf.get("compile-cache.dir", "")
    if cache_dir:
        from presto_tpu import compilecache

        compilecache.enable_persistent_cache(cache_dir)
    default_catalog = conf.get(
        "default-catalog", sorted(catalogs)[0]
    )
    page_rows = int(conf.get("page-rows", str(1 << 18)))
    # deployment-tier session defaults (reference: config-level system
    # session property defaults): split-batch.size seeds
    # split_batch_size for every query that doesn't override it —
    # e.g. split-batch.size=64 forces split batching on, =false pins
    # per-split launches fleet-wide
    session_defaults = dict(kw.pop("session_defaults", None) or {})
    if conf.get("split-batch.size"):
        session_defaults.setdefault(
            "split_batch_size", conf["split-batch.size"]
        )
    # device-memory.budget seeds the HBM governor's budget for every
    # query that doesn't override it (exec/membudget.py; 0 = auto)
    if conf.get("device-memory.budget"):
        session_defaults.setdefault(
            "device_memory_budget", conf["device-memory.budget"]
        )
    # fault-tolerance tier defaults (ISSUE 5): task-retry.attempts /
    # task-retry.backoff-ms govern DCN task re-dispatch and the
    # executor's device-OOM degradation ladder; query.max-run-time-ms
    # is the fleet-wide query deadline (reference: query.max-run-time)
    for etc_key, prop in (
        ("task-retry.attempts", "task_retry_attempts"),
        ("task-retry.backoff-ms", "retry_backoff_ms"),
        ("query.max-run-time-ms", "query_max_run_time"),
    ):
        if conf.get(etc_key):
            session_defaults.setdefault(prop, conf[etc_key])
    return PrestoTpuServer(
        catalogs, port=port, default_catalog=default_catalog,
        memory_budget_bytes=mem, page_rows=page_rows,
        session_defaults=session_defaults or None, **kw,
    )
