"""Deployment config: the reference's ``etc/`` layout.

Reference: presto-server's config tiers (SURVEY §6.6) —
``etc/config.properties`` (node/service keys, airlift @Config binding)
and ``etc/catalog/<name>.properties`` (one file per catalog; the
``connector.name`` key selects a ConnectorFactory, remaining keys are
connector-specific). Ours parses the same shapes into engine objects so
a reference-style deployment directory drives the server unchanged:

    etc/config.properties        http-server.http.port=8080
                                 query.max-memory-bytes=268435456
    etc/catalog/tpch.properties  connector.name=tpch
                                 tpch.scale-factor=1.0

Unknown connector names or malformed files raise at load (reference:
unknown config keys are a startup error).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional


def parse_properties(path: str) -> Dict[str, str]:
    """Java-style .properties subset: key=value lines, #/! comments,
    whitespace trimmed (reference: airlift loads these via
    java.util.Properties)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith(("#", "!")):
                continue
            if "=" not in line:
                raise ValueError(
                    f"{path}:{lineno}: expected key=value, got {line!r}"
                )
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


# connector.name -> factory(props) -> Connector (reference:
# ConnectorFactory registry in ConnectorManager; plugins extend it via
# register_connector_factory)
_FACTORIES: Dict[str, Callable] = {}


def register_connector_factory(name: str, factory: Callable) -> None:
    _FACTORIES[name] = factory


def _builtin_factories() -> Dict[str, Callable]:
    def tpch(props):
        from presto_tpu.connectors.tpch import TpchConnector

        return TpchConnector(
            scale=float(props.get("tpch.scale-factor", "0.01"))
        )

    def tpcds(props):
        from presto_tpu.connectors.tpcds import TpcdsConnector

        return TpcdsConnector(
            scale=float(props.get("tpcds.scale-factor", "0.01"))
        )

    def memory(props):
        from presto_tpu.connectors.memory import MemoryConnector

        return MemoryConnector()

    def blackhole(props):
        from presto_tpu.connectors.blackhole import BlackholeConnector

        return BlackholeConnector()

    def stream(props):
        from presto_tpu.connectors.stream import StreamConnector

        return StreamConnector()

    return {"tpch": tpch, "tpcds": tpcds, "memory": memory,
            "blackhole": blackhole, "stream": stream}


def load_catalogs(etc_dir: str) -> Dict[str, object]:
    """Build the catalog map from etc/catalog/*.properties (reference:
    StaticCatalogStore scanning the catalog config dir)."""
    catalog_dir = os.path.join(etc_dir, "catalog")
    factories = dict(_builtin_factories())
    factories.update(_FACTORIES)
    catalogs: Dict[str, object] = {}
    if not os.path.isdir(catalog_dir):
        return catalogs
    for fname in sorted(os.listdir(catalog_dir)):
        if not fname.endswith(".properties"):
            continue
        name = fname[: -len(".properties")]
        props = parse_properties(os.path.join(catalog_dir, fname))
        cname = props.get("connector.name")
        if not cname:
            raise ValueError(
                f"{fname}: missing required key connector.name"
            )
        factory = factories.get(cname)
        if factory is None:
            raise ValueError(
                f"{fname}: unknown connector.name {cname!r} "
                f"(known: {sorted(factories)})"
            )
        catalogs[name] = factory(props)
    return catalogs


# ---------------------------------------------------------------------
# THE etc-key <-> session-property registry (reference: airlift @Config
# bindings — every SystemSessionProperties entry has a config-file
# counterpart so a deployment can pin fleet-wide defaults without SET
# SESSION). One mapping, consumed three ways:
#
#   - server_from_etc seeds PrestoTpuServer session_defaults from any
#     of these keys found in etc/config.properties;
#   - tools/lint's session-props rule fails the build when a session
#     property lacks an etc key here (or an etc key names a property
#     that no longer exists);
#   - tests/test_config_etc.py generates its plumbing assertions from
#     this dict instead of a hand-maintained list.
#
# Keys marked in _ETC_STRUCTURAL_KEYS are consumed by the server
# wiring itself (constructor arguments / process-global config) rather
# than seeded as session defaults.
ETC_SESSION_KEYS: Dict[str, str] = {
    "tpu-offload.enabled": "tpu_offload_enabled",
    "join-distribution-type": "join_distribution_type",
    "broadcast-join.rows": "broadcast_join_rows",
    "agg-gather.capacity": "agg_gather_capacity",
    "page-rows": "page_rows",
    "array-agg.max-elements": "array_agg_max_elements",
    "query.max-memory-bytes": "query_max_memory_bytes",
    "hash-partition-count": "hash_partition_count",
    "pallas-join.enabled": "pallas_join_enabled",
    "mesh-exchange.mode": "mesh_exchange_mode",
    "spill.threshold-bytes": "spill_threshold_bytes",
    "generated-join.enabled": "generated_join_enabled",
    "agg-optimistic.rows": "agg_optimistic_rows",
    "agg-compact.enabled": "agg_compact_enabled",
    "join.max-build-rows": "max_join_build_rows",
    "spill.host-bytes": "host_spill_bytes",
    "spill.disk-bytes": "disk_spill_bytes",
    "spill.path": "spill_path",
    "late-materialization.enabled": "late_materialization_enabled",
    "fused-partial-agg.enabled": "fused_partial_agg_enabled",
    "split-batch.size": "split_batch_size",
    "compile-cache.dir": "compile_cache_dir",
    "device-memory.budget": "device_memory_budget",
    "plan-check.enabled": "plan_check",
    "task-retry.attempts": "task_retry_attempts",
    "task-retry.backoff-ms": "retry_backoff_ms",
    "query.max-run-time-ms": "query_max_run_time",
    "join-skew.rebalance": "join_skew_rebalance",
    "adaptive-execution": "adaptive_execution",
    "adaptive.max-replans": "adaptive_max_replans",
    "stage-scheduler": "stage_scheduler",
    "speculation.enabled": "speculation_enabled",
    "spool-exchange.bytes": "spool_exchange_bytes",
    "device-exchange.enabled": "device_exchange_enabled",
    "buffer-donation.enabled": "buffer_donation_enabled",
    "query-trace.enabled": "query_trace_enabled",
    "query-trace.dir": "query_trace_dir",
    "stats-profile.dir": "stats_profile_dir",
    "result-cache.enabled": "result_cache_enabled",
    "result-cache.bytes": "result_cache_bytes",
    "result-cache.ttl-ms": "result_cache_ttl_ms",
    "result-cache.persist-dir": "result_cache_persist_dir",
    "result-cache.remote-probe": "result_cache_remote_probe",
    "result-cache.subsumption": "result_cache_subsumption",
    "ivm.enabled": "ivm_enabled",
    "stream-tail.enabled": "stream_tail_enabled",
    "stream-poll.ms": "stream_poll_ms",
    "cross-query-batching": "cross_query_batching",
    "cross-query-batch.wait-ms": "cross_query_batch_wait_ms",
    "checkpoint.enabled": "checkpoint_enabled",
    "checkpoint.dir": "checkpoint_dir",
}

# consumed structurally by server_from_etc (constructor args /
# process-global config), never seeded as session defaults — a session
# default for page_rows would OVERRIDE the constructor value per-query
# (session.is_set wins), and compile-cache.dir is enabled ONCE at
# startup (seeding it would re-run the process-global cache setup on
# every query's apply_session)
_ETC_STRUCTURAL_KEYS = frozenset({
    "page-rows", "query.max-memory-bytes", "compile-cache.dir",
    "checkpoint.dir",
})


def load_node_config(etc_dir: str) -> Dict[str, str]:
    """etc/config.properties, empty when absent (reference: the node/
    service tier; keys consumed by serve_from_etc below)."""
    path = os.path.join(etc_dir, "config.properties")
    if not os.path.exists(path):
        return {}
    return parse_properties(path)


def server_from_etc(etc_dir: str, port: Optional[int] = None, **kw):
    """A PrestoTpuServer wired entirely from an etc/ directory —
    the reference's deployment story (bin/launcher reads etc/)."""
    from presto_tpu.server.http_server import PrestoTpuServer

    conf = load_node_config(etc_dir)
    catalogs = load_catalogs(etc_dir)
    if not catalogs:
        raise ValueError(
            f"no catalogs found under {etc_dir}/catalog/*.properties"
        )
    if port is None:
        port = int(conf.get("http-server.http.port", "8080"))
    mem = int(conf.get("query.max-memory-bytes", "0")) or None
    # persistent compile cache (reference analog: compiled-artifact
    # reuse across queries): one dir per machine outlives every server
    # process pointed at it
    cache_dir = conf.get("compile-cache.dir", "")
    if cache_dir:
        from presto_tpu import compilecache

        compilecache.enable_persistent_cache(cache_dir)
    default_catalog = conf.get(
        "default-catalog", sorted(catalogs)[0]
    )
    page_rows = int(conf.get("page-rows", str(1 << 18)))
    # deployment-tier session defaults (reference: config-level system
    # session property defaults): EVERY session property is seedable
    # from its registered etc key (ETC_SESSION_KEYS — e.g.
    # split-batch.size=64 forces split batching fleet-wide,
    # task-retry.attempts=0 pins the classic fail-query model);
    # structural keys are consumed by the constructor wiring above
    session_defaults = dict(kw.pop("session_defaults", None) or {})
    for etc_key, prop in ETC_SESSION_KEYS.items():
        if etc_key in _ETC_STRUCTURAL_KEYS:
            continue
        if conf.get(etc_key):
            session_defaults.setdefault(prop, conf[etc_key])
    # durable coordinator journal directory (structural: bound ONCE to
    # the server process; the checkpoint_dir session prop covers the
    # per-session override path)
    ckpt_dir = conf.get("checkpoint.dir", "")
    if ckpt_dir:
        kw.setdefault("checkpoint_dir", ckpt_dir)
    return PrestoTpuServer(
        catalogs, port=port, default_catalog=default_catalog,
        memory_budget_bytes=mem, page_rows=page_rows,
        session_defaults=session_defaults or None, **kw,
    )
