"""Interactive CLI (reference: presto-cli — airline+jline console).

Usage:
    python -m presto_tpu.cli --serve [--scale 0.01] [--port 8080]
        start an in-process coordinator with the tpch + memory +
        blackhole catalogs and drop into the shell against it
    python -m presto_tpu.cli --server http://host:port
        connect to a running coordinator
    python -m presto_tpu.cli --execute "select 1" [--server ...]
        run one statement and exit
"""

from __future__ import annotations

import argparse
import sys

from presto_tpu.client import StatementClient


def _fmt_table(columns, rows) -> str:
    if not columns:
        return ""
    names = [c["name"] for c in columns]
    cells = [[("NULL" if v is None else str(v)) for v in r] for r in rows]
    widths = [
        max(len(n), *(len(r[i]) for r in cells)) if cells else len(n)
        for i, n in enumerate(names)
    ]
    def line(vals):
        return " | ".join(v.ljust(w) for v, w in zip(vals, widths))
    sep = "-+-".join("-" * w for w in widths)
    out = [line(names), sep]
    out.extend(line(r) for r in cells)
    return "\n".join(out)


def _run_one(client: StatementClient, sql: str) -> int:
    res = client.execute(sql)
    if res.error:
        print(f"Query {res.query_id} failed: "
              f"{res.error.get('errorName')}: {res.error.get('message')}",
              file=sys.stderr)
        return 1
    if res.update_type:
        print(res.update_type)
    if res.columns:
        print(_fmt_table(res.columns, res.rows))
        print(f"({len(res.rows)} row{'s' if len(res.rows) != 1 else ''})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="presto-tpu")
    ap.add_argument("--server", default="http://127.0.0.1:8080")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="default")
    ap.add_argument("--user", default="presto")
    ap.add_argument("--execute", "-e", help="run one statement and exit")
    ap.add_argument("--serve", action="store_true",
                    help="start an in-process coordinator first")
    ap.add_argument("--port", type=int, default=None,
                    help="listen port for --serve (default: the etc "
                    "config's http-server.http.port when --etc-dir is "
                    "given, else 8080)")
    ap.add_argument("--scale", type=float, default=0.01,
                    help="tpch catalog scale factor for --serve")
    ap.add_argument("--etc-dir",
                    help="deployment config directory: "
                    "etc/config.properties + etc/catalog/*.properties "
                    "(reference etc/ layout; overrides --scale)")
    args = ap.parse_args(argv)

    server_url = args.server
    srv = None
    if args.serve:
        if args.etc_dir:
            from presto_tpu.config import server_from_etc

            srv = server_from_etc(args.etc_dir, port=args.port)
        else:
            from presto_tpu.connectors.blackhole import (
                BlackholeConnector,
            )
            from presto_tpu.connectors.memory import MemoryConnector
            from presto_tpu.connectors.tpch import TpchConnector
            from presto_tpu.server import PrestoTpuServer

            srv = PrestoTpuServer(
                {
                    "tpch": TpchConnector(scale=args.scale),
                    "memory": MemoryConnector(),
                    "blackhole": BlackholeConnector(),
                },
                port=args.port if args.port is not None else 8080,
            )
        port = srv.start()
        server_url = f"http://127.0.0.1:{port}"
        print(f"coordinator listening on {server_url}")

    client = StatementClient(
        server=server_url, user=args.user,
        catalog=args.catalog, schema=args.schema,
    )
    try:
        if args.execute:
            return _run_one(client, args.execute)
        # REPL
        buf = ""
        while True:
            try:
                prompt = "presto-tpu> " if not buf else "        ...> "
                line = input(prompt)
            except EOFError:
                break
            if not buf and line.strip().lower() in ("quit", "exit"):
                break
            buf += (" " if buf else "") + line
            if buf.rstrip().endswith(";") or not buf.strip():
                sql = buf.rstrip().rstrip(";")
                buf = ""
                if sql.strip():
                    _run_one(client, sql)
        return 0
    finally:
        if srv:
            srv.stop()


if __name__ == "__main__":
    sys.exit(main())
