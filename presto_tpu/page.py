"""Columnar Page/Block model as JAX pytrees.

Reference: presto-spi spi/Page.java (positionCount + Block[]) and
spi/block/* (LongArrayBlock, VariableWidthBlock, DictionaryBlock,
RunLengthEncodedBlock, ...). The reference moves variable-length Pages between
operators; XLA wants static shapes, so our Page is a **fixed-capacity** batch:

  - every Block array has length ``capacity`` (static, padded),
  - a per-page ``valid: bool[capacity]`` mask is the selection vector
    (reference analog: PageProcessor's selectedPositions),
  - per-block ``nulls: bool[capacity]`` masks SQL NULLs (True = null),
  - strings are DictionaryBlocks: int32 codes + a host-side Dictionary.

Filtering flips bits in ``valid``; physical row compaction happens only at
exchange/output boundaries (presto_tpu.ops.compact). This keeps every operator
a statically-shaped XLA program — the TPU translation of the reference's
"process a Page at a time" discipline.

Pages are registered pytrees: block data and masks are leaves (traced), types
and dictionaries are static aux data (hashable, drive jit specialization).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T


def _round_up(n: int, multiple: int = 8) -> int:
    return ((max(n, 1) + multiple - 1) // multiple) * multiple


class Dictionary:
    """Immutable host-side value dictionary for string/binary blocks.

    Reference: spi/block/DictionaryBlock.java keeps a Block of distinct values
    plus int positions; ours keeps a numpy object array of Python values and is
    hashable by content digest so it can ride in jit static aux data without
    recompiling per identical dictionary.
    """

    __slots__ = ("values", "_index", "_hash")

    def __init__(self, values: Sequence[Any]):
        vals = list(values)
        # element-wise fill: np.array(list_of_equal_length_tuples)
        # would build a 2-D object array, breaking decode/gather for
        # complex-typed (array/map/row tuple) values
        arr = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            arr[i] = v
        self.values = arr
        self._index = {v: i for i, v in enumerate(vals)}
        self._hash = hash(tuple(vals))

    def __len__(self) -> int:
        return len(self.values)

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Dictionary)
            and self._hash == other._hash
            and len(self.values) == len(other.values)
            and all(a == b for a, b in zip(self.values, other.values))
        )

    def code_of(self, value: Any) -> int:
        """Code for value, or -1 if absent (-1 never matches any row code)."""
        return self._index.get(value, -1)

    def decode(self, codes: np.ndarray) -> np.ndarray:
        out = np.empty(codes.shape, dtype=object)
        in_range = (codes >= 0) & (codes < len(self.values))
        out[in_range] = self.values[codes[in_range]]
        out[~in_range] = None
        return out

    def sort_rank(self) -> np.ndarray:
        """rank[code] = position of that value in sorted order — makes code
        comparison order-correct for ORDER BY on dictionary columns."""
        order = np.argsort(self.values, kind="stable")
        rank = np.empty(len(self.values), dtype=np.int32)
        rank[order] = np.arange(len(self.values), dtype=np.int32)
        return rank

    def has_duplicate_values(self) -> bool:
        """Transform-produced dictionaries (substr/lower/...) may map many
        codes to one value; equality on raw codes is then wrong (see
        ops/keys.equality_encoding). Subclasses with unique-by-construction
        values override to False without materializing."""
        return len(self._index) < len(self.values)

    def __repr__(self) -> str:  # pragma: no cover
        head = ", ".join(repr(v) for v in self.values[:4])
        more = "..." if len(self.values) > 4 else ""
        return f"Dictionary([{head}{more}], n={len(self.values)})"


@dataclasses.dataclass
class Block:
    """One column of a Page.

    data: jnp array [capacity] (dtype per SqlType.device_dtype). For long
          decimals (p > 18), a tuple (hi, lo) of int64 arrays.
    nulls: optional bool[capacity], True = SQL NULL. None = no nulls.
    type: SqlType (static aux).
    dictionary: host Dictionary for string/binary types (static aux).
    """

    data: Union[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]
    type: T.SqlType
    nulls: Optional[jnp.ndarray] = None
    dictionary: Optional[Dictionary] = None

    @property
    def capacity(self) -> int:
        arr = self.data[0] if isinstance(self.data, tuple) else self.data
        return arr.shape[0]

    def nulls_or_false(self) -> jnp.ndarray:
        if self.nulls is None:
            return jnp.zeros((self.capacity,), dtype=jnp.bool_)
        return self.nulls

    def with_data(self, data, nulls="keep") -> "Block":
        return Block(
            data=data,
            type=self.type,
            nulls=self.nulls if nulls == "keep" else nulls,
            dictionary=self.dictionary,
        )

    def take(self, idx, extra_nulls=None) -> "Block":
        """Row-indirection gather: output row j = self row idx[j], with
        ``extra_nulls`` ORed over the gathered null mask.

        The Block-level primitive behind DictionaryBlock-style late
        materialization (exec/latemat.py defers carried join columns as
        row-id indirections and takes the values once, at the first
        value consumer) and ordinary row gathers (ops/compact.
        gather_rows). Callers clamp idx into range; masked-off rows may
        gather garbage that validity/null masks hide."""
        if isinstance(self.data, tuple):
            data = tuple(d[idx] for d in self.data)
        else:
            data = self.data[idx]
        nulls = self.nulls[idx] if self.nulls is not None else None
        if extra_nulls is not None:
            nulls = (
                extra_nulls if nulls is None else (nulls | extra_nulls)
            )
        return Block(
            data=data, type=self.type, nulls=nulls,
            dictionary=self.dictionary,
        )

    def tree_flatten(self):
        children = (self.data, self.nulls)
        aux = (self.type, self.dictionary, self.nulls is None)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        typ, dictionary, _nulls_absent = aux
        data, nulls = children
        return cls(data=data, type=typ, nulls=nulls, dictionary=dictionary)


jax.tree_util.register_pytree_node(
    Block, Block.tree_flatten, Block.tree_unflatten
)


@dataclasses.dataclass
class Page:
    """A columnar batch: blocks + selection mask.

    Reference: spi/Page.java — but positionCount becomes (capacity, valid[]).
    """

    blocks: Tuple[Block, ...]
    valid: jnp.ndarray  # bool[capacity]

    @property
    def capacity(self) -> int:
        return self.valid.shape[0]

    @property
    def channel_count(self) -> int:
        return len(self.blocks)

    def num_rows(self) -> jnp.ndarray:
        """Traced count of selected rows (reference: getPositionCount)."""
        return jnp.sum(self.valid.astype(jnp.int64))

    def block(self, channel: int) -> Block:
        return self.blocks[channel]

    def with_valid(self, valid: jnp.ndarray) -> "Page":
        return Page(blocks=self.blocks, valid=valid)

    def with_blocks(self, blocks: Sequence[Block]) -> "Page":
        return Page(blocks=tuple(blocks), valid=self.valid)

    def select_channels(self, channels: Sequence[int]) -> "Page":
        return Page(
            blocks=tuple(self.blocks[c] for c in channels), valid=self.valid
        )

    def append_blocks(self, blocks: Sequence[Block]) -> "Page":
        return Page(blocks=self.blocks + tuple(blocks), valid=self.valid)

    def tree_flatten(self):
        return (self.blocks, self.valid), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        blocks, valid = children
        return cls(blocks=tuple(blocks), valid=valid)

    # ---------------------------------------------------------------- host IO
    @staticmethod
    def from_arrays(
        columns: Sequence[Any],
        types: Sequence[T.SqlType],
        *,
        capacity: Optional[int] = None,
        dictionaries: Optional[Sequence[Optional[Dictionary]]] = None,
    ) -> "Page":
        """Build a Page from host data (numpy arrays or Python lists; None =
        NULL). String columns are dictionary-encoded here (ingest boundary —
        reference analog: connector PageSource building Blocks)."""
        if not columns:
            raise ValueError("page needs at least one column")
        n = len(columns[0])
        cap = capacity or _round_up(n)
        dictionaries = dictionaries or [None] * len(columns)
        blocks: List[Block] = []
        for col, typ, dic in zip(columns, types, dictionaries):
            blocks.append(_encode_column(col, typ, cap, dic))
        valid = np.zeros(cap, dtype=bool)
        valid[:n] = True
        # metered h2d boundary (exec/xfer.py, lazy import like
        # to_pylist): page construction from host values is a real
        # device staging the transfer ledger must see
        from presto_tpu.exec import xfer as XF

        return Page(blocks=tuple(blocks),
                    valid=XF.to_device(valid, label="page-build"))

    def to_pylist(self) -> List[tuple]:
        """Materialize selected rows as Python tuples (test/client boundary).

        Reference analog: testing/MaterializedResult.
        """
        # metered d2h boundary (exec/xfer.py; imported lazily — page
        # loads before the exec package during engine import)
        from presto_tpu.exec import xfer as XF

        valid = XF.np_host(self.valid, label="decode-valid")
        rows_idx = np.nonzero(valid)[0]
        cols = []
        for blk in self.blocks:
            cols.append(_decode_block(blk, rows_idx))
        return [tuple(col[i] for col in cols) for i in range(len(rows_idx))]


jax.tree_util.register_pytree_node(Page, Page.tree_flatten, Page.tree_unflatten)


def _encode_column(
    col: Any,
    typ: T.SqlType,
    cap: int,
    dictionary: Optional[Dictionary],
) -> Block:
    vals = list(col) if not isinstance(col, np.ndarray) else col.tolist()
    n = len(vals)
    if n > cap:
        raise ValueError(f"column length {n} exceeds capacity {cap}")
    null_mask = np.array([v is None for v in vals] + [True] * (cap - n))
    has_nulls = bool(null_mask[:n].any())

    if typ.is_dictionary_encoded:
        if dictionary is None:
            distinct = sorted({v for v in vals if v is not None})
            dictionary = Dictionary(distinct)
        codes = np.zeros(cap, dtype=np.int32)
        for i, v in enumerate(vals):
            if v is None:
                continue
            code = dictionary.code_of(v)
            if code < 0:
                raise ValueError(
                    f"value {v!r} not in supplied dictionary"
                )
            codes[i] = code
    # metered h2d boundary (exec/xfer.py): every encoded column stages
    # host values onto the device — the ingest crossing the transfer
    # ledger must see (lazy import; page loads before the exec package)
    from presto_tpu.exec import xfer as XF

    if typ.is_dictionary_encoded:
        return Block(
            data=XF.to_device(codes, label="page-build"),
            type=typ,
            nulls=(XF.to_device(null_mask, label="page-build")
                   if has_nulls else None),
            dictionary=dictionary,
        )

    if isinstance(typ, T.DecimalType) and not typ.is_short:
        hi = np.zeros(cap, dtype=np.int64)
        lo = np.zeros(cap, dtype=np.int64)
        for i, v in enumerate(vals):
            if v is None:
                continue
            u = int(v) & ((1 << 128) - 1)
            lo[i] = np.int64((u & ((1 << 64) - 1)) - (1 << 64) if (u >> 63) & 1 else u & ((1 << 64) - 1))
            hi[i] = np.int64((int(v) >> 64))
        return Block(
            data=(XF.to_device(hi, label="page-build"),
                  XF.to_device(lo, label="page-build")),
            type=typ,
            nulls=(XF.to_device(null_mask, label="page-build")
                   if has_nulls else None),
        )

    np_dtype = typ.numpy_dtype
    arr = np.zeros(cap, dtype=np_dtype)
    for i, v in enumerate(vals):
        if v is not None:
            arr[i] = v
    return Block(
        data=XF.to_device(arr, label="page-build"),
        type=typ,
        nulls=(XF.to_device(null_mask, label="page-build")
               if has_nulls else None),
    )


def _collect_elem_decoder(elem_t, dictionary):
    """int64-encoded collect-state slot -> Python element value
    (inverse of exec/executor._collect_encode)."""
    if dictionary is not None:
        values = dictionary.values

        def dec(v):
            return values[int(np.clip(v, 0, len(values) - 1))]
        return dec
    if isinstance(elem_t, (T.DoubleType, T.RealType)):
        import math

        def dec_float(v):
            v = int(v)
            if v == 0:
                return 0.0
            mag = abs(v)
            e = (mag >> 52) - 1100
            frac = mag & ((1 << 52) - 1)
            out = math.ldexp(0.5 + frac * 2.0**-53, e + 1)
            return -out if v < 0 else out
        return dec_float
    if isinstance(elem_t, T.BooleanType):
        return lambda v: bool(v)
    return lambda v: int(v)


def _decode_block(blk: Block, rows_idx: np.ndarray) -> list:
    from presto_tpu.exec import xfer as XF

    nulls = XF.np_host(blk.nulls) if blk.nulls is not None else None
    if (isinstance(blk.type, (T.ArrayType, T.MapType))
            and isinstance(blk.data, tuple)):
        # collect-state result: (vals2d, elem-null-flags2d, counts) for
        # array_agg; (k2d, v2d, value-null-flags2d, counts) for map_agg
        *mats, counts = blk.data
        mats = [XF.np_host(m)[rows_idx] for m in mats]
        counts = XF.np_host(counts)[rows_idx]
        if isinstance(blk.type, T.ArrayType):
            dec = _collect_elem_decoder(blk.type.element, blk.dictionary)
            vals = [
                tuple(
                    None if nf else dec(v)
                    for v, nf in zip(mats[0][i, : int(c)],
                                     mats[1][i, : int(c)])
                )
                for i, c in enumerate(counts)
            ]
        else:
            kdec = _collect_elem_decoder(blk.type.key, blk.dictionary)
            vdec = _collect_elem_decoder(blk.type.value, None)
            vals = [
                tuple(
                    (kdec(k), None if nf else vdec(v))
                    for k, v, nf in zip(mats[0][i, : int(c)],
                                        mats[1][i, : int(c)],
                                        mats[2][i, : int(c)])
                )
                for i, c in enumerate(counts)
            ]
    elif isinstance(blk.data, tuple):
        hi = XF.np_host(blk.data[0])[rows_idx].astype(object)
        lo = XF.np_host(blk.data[1])[rows_idx].astype(object)
        vals = [(int(h) << 64) | (int(l) & ((1 << 64) - 1)) for h, l in zip(hi, lo)]
    elif blk.dictionary is not None:
        codes = XF.np_host(blk.data)[rows_idx]
        vals = list(blk.dictionary.decode(codes))
    else:
        arr = XF.np_host(blk.data)[rows_idx]
        if arr.dtype == np.bool_:
            vals = [bool(v) for v in arr]
        elif np.issubdtype(arr.dtype, np.integer):
            vals = [int(v) for v in arr]
        else:
            vals = [float(v) for v in arr]
    if nulls is not None:
        sel = nulls[rows_idx]
        vals = [None if is_null else v for v, is_null in zip(vals, sel)]
    return vals
