"""Connectors: pluggable table providers (reference: presto-spi
ConnectorFactory / ConnectorMetadata / ConnectorSplitManager /
ConnectorPageSourceProvider; modules presto-tpch, presto-memory,
presto-blackhole). A connector here supplies schemas, row counts, and Pages;
split streaming maps to chunked page generation over row ranges."""

from presto_tpu.connectors.base import (  # noqa: F401
    Connector,
    ColumnSchema,
    TableSchema,
)
