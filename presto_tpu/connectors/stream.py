"""Append-log stream connector: the engine's streaming-source column.

Reference: PAPER.md §1 lists streaming sources (Kafka) among the
reference's connectors. The TPU translation keeps the part that
matters to the engine — an APPEND-ONLY log whose read position is a
monotone offset — and drops the broker: rows append to a host-RAM
column log, the log's ``snapshot_version`` IS its offset, and readers
choose between three composable views of the same data:

  - a FULL scan (``pages``/``splits``): the log looks like any other
    table, so every existing operator, cache path, and oracle harness
    composes unchanged;
  - a DELTA scan (``scan_from(offset)``): only the pages appended
    since ``offset`` — the O(new rows) input of an incremental view
    refresh (streaming/ivm.py);
  - a PINNED window (``StreamWindowConnector``): a fixed ``[lo, hi)``
    row range presented AS the table, whose snapshot token carries the
    PINNED offset instead of the live head — so a result-cache entry
    built at offset N keeps hitting for a reader pinned at N while
    the log keeps growing (cache/rules.stream_watermark + the store's
    advance-on-append reclaim).

Offsets are row counts: ``append(table, rows)`` extends the columns
under the connector's condition and wakes every ``wait_for_offset``
long-poller (the tailing /v1/statement cursors). Appends never rewrite
existing rows, and dictionary codes are assigned in FIRST-SEEN order
and only ever appended — the encoded prefix of the log is immutable,
which is what makes pinned-offset replays byte-stable.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.connectors.base import (
    ColumnSchema,
    Connector,
    Split,
    TableSchema,
)
from presto_tpu.obs.sanitizer import (
    make_condition,
    register_owner,
)
from presto_tpu.page import Dictionary, Page


class _StreamTable:
    """One append-only log: per-column Python value lists plus
    first-seen-order dictionary value lists for encoded columns.
    Mutated only under the owning connector's condition; the prefix
    below the published offset is immutable."""

    def __init__(self, schema: TableSchema):
        self.schema = schema
        self.columns: List[list] = [[] for _ in schema.columns]
        # encoded columns: value list in FIRST-SEEN order (append-only
        # — codes for already-appended rows never change), with a
        # persistent membership set so an append costs O(batch), not
        # O(total distinct values)
        self.dict_values: Dict[str, list] = {
            c.name: [] for c in schema.columns
            if c.type.is_dictionary_encoded
        }
        self._dict_seen: Dict[str, set] = {
            name: set() for name in self.dict_values
        }
        self.offset = 0  # rows appended so far == snapshot offset
        self.appends = 0

    def extend(self, rows: Sequence[tuple]) -> None:
        # validate the WHOLE batch before mutating anything: a
        # mid-batch failure must never leave orphan rows below the
        # published offset (the prefix is immutable by contract)
        for r in rows:
            if len(r) != len(self.columns):
                raise ValueError(
                    f"row arity {len(r)} != schema arity "
                    f"{len(self.columns)} for stream "
                    f"{self.schema.name!r}"
                )
        for r in rows:
            for col, v in zip(self.columns, r):
                col.append(v)
        for name, seen in self._dict_seen.items():
            idx = self.schema.column_index(name)
            vals = self.dict_values[name]
            for v in self.columns[idx][self.offset:]:
                if v is not None and v not in seen:
                    vals.append(v)
                    seen.add(v)
        self.offset += len(rows)
        self.appends += 1


class StreamConnector(Connector):
    """See module docstring. ``append_only`` marks the connector for
    the cache plane (runner._invalidate_caches advances instead of
    discarding) and the tailing-cursor statement path."""

    name = "stream"
    append_only = True

    # lock discipline (tools/lint `locks` rule): the table map is
    # shared between appender threads, scan readers, and tail pollers
    _shared_attrs = ("_tables",)

    def __init__(self):
        self._tables: Dict[str, _StreamTable] = {}
        # one condition for the whole connector: appends notify every
        # tailing long-poller (per-table conditions would force the
        # registry to grow per CREATE, for no contention win at this
        # fan-in)
        self._cv = make_condition(
            "connectors.stream.StreamConnector._cv")
        register_owner(self, lock_attrs=("_cv",))

    # ------------------------------------------------------------ write
    def create_table(
        self,
        name: str,
        column_names: Sequence[str],
        column_types: Sequence[T.SqlType],
        rows: List[tuple],
        *,
        replace: bool = False,
    ) -> int:
        """CTAS entry (runner write path): a new log seeded with
        ``rows`` at offset len(rows). ``replace`` restarts the log —
        offsets restart too, so replace is a DDL event, not an append
        (pinned readers of the old log are invalidated by the runner's
        write path, same as DROP)."""
        schema = TableSchema(
            name,
            tuple(
                ColumnSchema(n, t)
                for n, t in zip(column_names, column_types)
            ),
        )
        with self._cv:
            if name in self._tables and not replace:
                raise ValueError(f"stream already exists: {name}")
            t = _StreamTable(schema)
            t.extend(list(rows))
            self._tables[name] = t
            self._cv.notify_all()
        return len(rows)

    def insert(self, name: str, rows: List[tuple]) -> int:
        """INSERT INTO == append (the runner's write path)."""
        self.append(name, rows)
        return len(rows)

    def append(self, table: str, rows: Sequence[tuple]) -> int:
        """THE log write: extend the columns, advance the offset,
        wake every tailing long-poller. Returns the new offset."""
        with self._cv:
            t = self._tables.get(table)
            if t is None:
                raise KeyError(f"no stream {table!r}")
            t.extend(list(rows))
            self._cv.notify_all()
            return t.offset

    def drop_table(self, name: str) -> None:
        with self._cv:
            if name not in self._tables:
                raise KeyError(f"no stream {name!r}")
            del self._tables[name]
            self._cv.notify_all()

    # ------------------------------------------------------------- read
    def tables(self) -> List[str]:
        with self._cv:
            return sorted(self._tables)

    def table_schema(self, table: str) -> TableSchema:
        t = self._tables.get(table)
        if t is None:
            raise KeyError(f"no stream {table!r}")
        return t.schema

    def row_count(self, table: str) -> int:
        return self.offset(table)

    def offset(self, table: str) -> int:
        """The log's current offset (== rows appended). THE monotone
        value snapshot_version, delta scans, IVM watermarks, and tail
        cursors all key on."""
        with self._cv:
            t = self._tables.get(table)
            return t.offset if t is not None else 0

    def appends_seen(self, table: str) -> int:
        with self._cv:
            t = self._tables.get(table)
            return t.appends if t is not None else 0

    def snapshot_version(self, table: str) -> Optional[str]:
        """``off:<offset>`` — monotone by construction. A write moves
        it forward (never sideways), which is what lets the cache
        plane ADVANCE entries over this connector instead of
        discarding them (cache/store.advance_tables)."""
        with self._cv:
            t = self._tables.get(table)
            if t is None:
                return None
            return f"off:{t.offset}"

    def pinned_offset(self, table: str) -> Optional[int]:
        """None: a bare StreamConnector scan reads the LIVE log head
        (its cache entries key to the moving offset token and are
        reclaimed on append). StreamWindowConnector overrides with its
        pinned upper bound — the cache/rules.stream_watermark probe."""
        return None

    def wait_for_offset(self, table: str, min_offset: int,
                        timeout_s: float) -> int:
        """Long-poll until the log advances PAST ``min_offset`` (or
        the timeout lapses); returns the current offset either way.
        The tailing-cursor poll primitive — Condition.wait releases
        the connector lock, so appenders are never blocked by
        pollers."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        with self._cv:
            while True:
                t = self._tables.get(table)
                cur = t.offset if t is not None else 0
                if cur > min_offset:
                    return cur
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return cur
                self._cv.wait(remaining)

    # ------------------------------------------------------ page plane
    def _snapshot_slice(self, table: str, lo: int, hi: int,
                        columns: Optional[Sequence[str]]):
        """(names, value slices, types, dictionaries) for rows
        [lo, hi) — taken under the condition so a concurrent append
        can never tear a slice (the prefix itself is immutable)."""
        with self._cv:
            t = self._tables.get(table)
            if t is None:
                raise KeyError(f"no stream {table!r}")
            names = (
                tuple(columns) if columns is not None
                else tuple(t.schema.column_names())
            )
            cols, types, dicts = [], [], []
            for nm in names:
                idx = t.schema.column_index(nm)
                cols.append(list(t.columns[idx][lo:hi]))
                types.append(t.schema.columns[idx].type)
                dv = t.dict_values.get(nm)
                dicts.append(
                    Dictionary(list(dv)) if dv is not None else None
                )
            return names, cols, types, dicts

    def page_for_split(
        self, split: Split, columns: Optional[Sequence[str]] = None
    ) -> Page:
        lo = split.start_row
        _names, cols, types, dicts = self._snapshot_slice(
            split.table, lo, lo + split.row_count, columns
        )
        return Page.from_arrays(cols, types, dictionaries=dicts)

    def scan_from(
        self,
        table: str,
        offset: int,
        columns: Optional[Sequence[str]] = None,
        target_rows: int = 1 << 20,
    ):
        """Delta pages: only the rows appended since ``offset``, up to
        the offset observed at call time (appends racing the scan show
        up in the NEXT delta). The incremental-refresh input plane."""
        hi = self.offset(table)
        lo = min(max(int(offset), 0), hi)
        start = lo
        while start < hi:
            n = min(target_rows, hi - start)
            yield self.page_for_split(
                Split(table, start, n), columns
            )
            start += n

    def host_rows(self, table: str, target_rows: int = 1 << 20):
        """Row tuples for the sqlite oracle (tests/oracle.py)."""
        hi = self.offset(table)
        with self._cv:
            t = self._tables[table]
            return list(zip(*[c[:hi] for c in t.columns])) \
                if t.columns and hi else []


class StreamWindowConnector:
    """A PINNED ``[lo, hi)`` row window of one stream table, presented
    AS the table: splits/pages/row_count cover exactly the window, and
    the snapshot token carries the pinned range instead of the live
    offset — so two readers pinned at the same range share cache
    entries FOREVER, no matter how far the log has advanced (the
    monotone-offset-token fix, ISSUE 14 satellite). The range is
    mutable via ``set_range`` so one wrapper (and one executor whose
    catalogs hold it) serves every refresh of a view: delta refreshes
    pin [watermark, head), full recomputes pin [0, head).

    Non-window tables delegate to the inner connector untouched."""

    append_only = True

    def __init__(self, inner, table: str, lo: int = 0,
                 hi: Optional[int] = None):
        self._inner = inner
        self._table = table
        self._lo = int(lo)
        self._hi = int(hi if hi is not None
                       else inner.offset(table) if table in
                       inner.tables() else 0)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def set_range(self, lo: int, hi: int) -> None:
        self._lo, self._hi = int(lo), int(hi)

    def snapshot_version(self, table: str) -> Optional[str]:
        if table != self._table:
            return self._inner.snapshot_version(table)
        # the PINNED token: stable while the log advances — monotone
        # offsets make "the prefix I asked for" a permanent identity
        return f"off:{self._hi}@{self._lo}"

    def pinned_offset(self, table: str) -> Optional[int]:
        if table != self._table:
            inner = getattr(self._inner, "pinned_offset", None)
            return inner(table) if inner is not None else None
        return self._hi

    def row_count(self, table: str) -> int:
        if table != self._table:
            return self._inner.row_count(table)
        return max(self._hi - self._lo, 0)

    def offset(self, table: str) -> int:
        if table != self._table:
            return self._inner.offset(table)
        return self._hi

    def splits(self, table: str, target_rows: int) -> List[Split]:
        if table != self._table:
            return self._inner.splits(table, target_rows)
        # the base chopper over THIS wrapper's windowed row_count
        # (page_for_split shifts the ranges into the pinned window)
        return Connector.splits(self, table, target_rows)

    def page_for_split(
        self, split: Split, columns: Optional[Sequence[str]] = None
    ) -> Page:
        if split.table != self._table:
            return self._inner.page_for_split(split, columns)
        shifted = Split(split.table, split.start_row + self._lo,
                        split.row_count)
        return self._inner.page_for_split(shifted, columns)

    def prune_splits(self, table, splits, constraint):
        if table != self._table:
            return self._inner.prune_splits(table, splits, constraint)
        return splits  # advisory; the residual Filter re-applies

    def pages(
        self,
        table: str,
        columns: Optional[Sequence[str]] = None,
        target_rows: int = 1 << 20,
        constraint=None,
    ):
        # must re-implement (not delegate): the inner pages() would
        # use the inner splits() and bypass the window
        splits = self.splits(table, target_rows)
        if constraint:
            splits = self.prune_splits(table, splits, constraint)
        for split in splits:
            if split.row_count:
                yield self.page_for_split(split, columns)

    def host_rows(self, table: str, target_rows: int = 1 << 20):
        if table != self._table:
            return self._inner.host_rows(table, target_rows)
        rows = self._inner.host_rows(table, target_rows)
        return rows[self._lo:self._hi]
