"""Blackhole connector: accepts any write and discards it; reads return
empty tables. Reference: presto-blackhole (BlackHoleConnector) — the null
sink/source used by perf tests and as a fixture double.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.connectors.base import (
    ColumnSchema,
    Connector,
    Split,
    TableSchema,
)
from presto_tpu.page import Page


class BlackholeConnector(Connector):
    name = "blackhole"

    def __init__(self):
        self._schemas: Dict[str, TableSchema] = {}

    def create_table(self, name, column_names, column_types, rows,
                     *, replace: bool = False) -> int:
        self._schemas[name] = TableSchema(
            name,
            tuple(
                ColumnSchema(n, t)
                for n, t in zip(column_names, column_types)
            ),
        )
        return len(rows)  # acknowledged, discarded

    def insert(self, name, rows) -> int:
        return len(rows)

    def drop_table(self, name) -> None:
        self._schemas.pop(name, None)

    def tables(self) -> List[str]:
        return sorted(self._schemas)

    def table_schema(self, table: str) -> TableSchema:
        if table not in self._schemas:
            raise KeyError(f"no table {table!r}")
        return self._schemas[table]

    def row_count(self, table: str) -> int:
        return 0

    def page_for_split(
        self, split: Split, columns: Optional[Sequence[str]] = None
    ) -> Page:  # pragma: no cover - zero splits are never generated
        raise AssertionError("blackhole tables have no rows")
