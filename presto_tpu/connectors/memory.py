"""In-memory connector: CREATE TABLE AS stores pages in host RAM; scans
stage them to the device (the real host->HBM path, unlike the tpch
generator which computes rows in HBM).

Reference: presto-memory (MemoryConnector, MemoryPagesStore,
MemoryPageSinkProvider) — named in BASELINE config 1.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.base import (
    ColumnSchema,
    Connector,
    Split,
    TableSchema,
)
from presto_tpu.page import Dictionary, Page


class _StoredTable:
    """Host-RAM column store (MemoryPagesStore analog): plain python/numpy
    columns plus per-column dictionaries for strings, built once at write
    time so scans stage straight into device pages."""

    def __init__(self, schema: TableSchema, rows: List[tuple]):
        self.schema = schema
        self.rows = rows
        self.dictionaries: Dict[str, Optional[Dictionary]] = {}
        cols = list(zip(*rows)) if rows else [
            [] for _ in schema.columns
        ]
        self.columns = [list(c) for c in cols]
        for col, cs in zip(self.columns, schema.columns):
            if cs.type.is_dictionary_encoded:
                distinct = sorted({v for v in col if v is not None})
                self.dictionaries[cs.name] = Dictionary(distinct)
            else:
                self.dictionaries[cs.name] = None
        # integer column arrays for split min/max pruning (the memory
        # connector's TupleDomain stats; reference: per-page stats kept
        # by storage connectors for predicate pushdown)
        self.int_cols: Dict[str, tuple] = {}
        for col, cs in zip(self.columns, schema.columns):
            t = cs.type
            if T.is_string(t) or T.is_floating(t):
                continue
            try:
                vals = np.array(
                    [0 if v is None else int(v) for v in col],
                    dtype=np.int64,
                )
            except (TypeError, ValueError, OverflowError):
                continue
            nulls = np.array([v is None for v in col], dtype=bool)
            self.int_cols[cs.name] = (vals, nulls)

    @property
    def row_count(self) -> int:
        return len(self.rows)


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self):
        self._tables: Dict[str, _StoredTable] = {}
        # per-table write counter: every mutation path bumps it, so
        # snapshot_version moves even when the row count does not
        # (UPDATE rewrites through create_table(replace=True) with the
        # same cardinality — a row-count-derived token would falsely
        # certify stale cached results; see Connector.snapshot_version)
        self._write_versions: Dict[str, int] = {}

    def _bump(self, name: str) -> None:
        self._write_versions[name] = (
            self._write_versions.get(name, 0) + 1
        )

    def snapshot_version(self, table: str) -> str:
        t = self._tables.get(table)
        return (f"w{self._write_versions.get(table, 0)}"
                f":r{t.row_count if t is not None else 0}")

    # ------------------------------------------------------------- write
    def create_table(
        self,
        name: str,
        column_names: Sequence[str],
        column_types: Sequence[T.SqlType],
        rows: List[tuple],
        *,
        replace: bool = False,
    ) -> int:
        if name in self._tables and not replace:
            raise ValueError(f"table already exists: {name}")
        schema = TableSchema(
            name,
            tuple(
                ColumnSchema(n, t)
                for n, t in zip(column_names, column_types)
            ),
        )
        self._tables[name] = _StoredTable(schema, list(rows))
        self._bump(name)
        return len(rows)

    def insert(self, name: str, rows: List[tuple]) -> int:
        t = self._tables.get(name)
        if t is None:
            raise KeyError(f"no table {name!r}")
        self._tables[name] = _StoredTable(t.schema, t.rows + list(rows))
        self._bump(name)
        return len(rows)

    def drop_table(self, name: str) -> None:
        if name not in self._tables:
            raise KeyError(f"no table {name!r}")
        del self._tables[name]
        self._bump(name)

    # -------------------------------------------------------------- read
    def tables(self) -> List[str]:
        return sorted(self._tables)

    def table_schema(self, table: str) -> TableSchema:
        t = self._tables.get(table)
        if t is None:
            raise KeyError(f"no table {table!r}")
        return t.schema

    def row_count(self, table: str) -> int:
        return self._tables[table].row_count

    def prune_splits(self, table, splits, constraint):
        """Per-split min/max pruning over the stored integer columns
        (TupleDomain pushdown, exec/pushdown.py; a split that is all-null
        in a constrained column can never match either)."""
        t = self._tables.get(table)
        if t is None:
            return splits
        out = []
        for s in splits:
            keep = True
            for col, lo, hi in constraint:
                stats = t.int_cols.get(col)
                if stats is None:
                    continue
                vals, nulls = stats
                seg = vals[s.start_row:s.start_row + s.row_count]
                ok = ~nulls[s.start_row:s.start_row + s.row_count]
                if not ok.any():
                    keep = False  # all-null: no comparison can pass
                    break
                smin, smax = seg[ok].min(), seg[ok].max()
                if (lo is not None and smax < lo) or (
                    hi is not None and smin > hi
                ):
                    keep = False
                    break
            if keep:
                out.append(s)
        return out

    def page_for_split(
        self, split: Split, columns: Optional[Sequence[str]] = None
    ) -> Page:
        t = self._tables[split.table]
        names = (
            tuple(columns) if columns is not None
            else tuple(t.schema.column_names())
        )
        lo, hi = split.start_row, split.start_row + split.row_count
        cols = []
        types = []
        dicts = []
        for nm in names:
            idx = t.schema.column_index(nm)
            cols.append(t.columns[idx][lo:hi])
            types.append(t.schema.columns[idx].type)
            dicts.append(t.dictionaries.get(nm))
        return Page.from_arrays(cols, types, dictionaries=dicts)
