"""Split-range filtering for multi-process (DCN) scans.

Reference: SOURCE_DISTRIBUTION split assignment — the coordinator's
SourcePartitionedScheduler streams each split to exactly one task
(presto-main execution/scheduler/SourcePartitionedScheduler.java).
The TPU translation assigns the designated fact table's splits
round-robin by worker index; every other table scans whole (the
broadcast-build / split-probe shape that keeps FK joins exact under
data parallelism). Generator connectors make a worker's scan of its
splits free of other workers' data by construction (scan==generate).
"""

from __future__ import annotations

from typing import Optional, Sequence

from presto_tpu.connectors.base import Split


class SplitFilterConnector:
    """Wraps a connector; worker ``index`` of ``count`` sees only its
    round-robin share of ``table``'s splits."""

    def __init__(self, inner, table: str, index: int, count: int):
        self._inner = inner
        self._table = table
        self._index = index
        self._count = count

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def splits(self, table: str, target_rows: int):
        splits = self._inner.splits(table, target_rows)
        if table != self._table:
            return splits
        mine = splits[self._index::self._count]
        return mine or [Split(table, 0, 0)]

    def pages(
        self,
        table: str,
        columns: Optional[Sequence[str]] = None,
        target_rows: int = 1 << 20,
        constraint=None,
    ):
        # must re-implement (not delegate): the inner pages() would call
        # the inner splits() and bypass the filter
        splits = self.splits(table, target_rows)
        if constraint:
            splits = self._inner.prune_splits(table, splits, constraint)
        for split in splits:
            if split.row_count:
                yield self._inner.page_for_split(split, columns)
