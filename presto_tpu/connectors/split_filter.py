"""Split-range filtering for multi-process (DCN) scans.

Reference: SOURCE_DISTRIBUTION split assignment — the coordinator's
SourcePartitionedScheduler streams each split to exactly one task
(presto-main execution/scheduler/SourcePartitionedScheduler.java).
The TPU translation assigns the designated fact table's splits
round-robin by worker index; every other table scans whole (the
broadcast-build / split-probe shape that keeps FK joins exact under
data parallelism). Generator connectors make a worker's scan of its
splits free of other workers' data by construction (scan==generate).
"""

from __future__ import annotations

from typing import Optional, Sequence

from presto_tpu.connectors.base import Split


class SplitFilterConnector:
    """Wraps a connector; worker ``index`` of ``count`` sees only its
    round-robin share of ``table``'s splits."""

    # pages() below IS the base per-split generation loop over this
    # wrapper's own splits() — safe for the executor's whole-pipeline
    # fusion (which drives splits()/gen_body directly), so a worker's
    # shipped scan→filter→project→partial-agg fragment compiles to one
    # program per split exactly like the local path. HashSplitConnector
    # must NOT set this: its pages() masks rows after generation.
    fused_scan_ok = True

    def __init__(self, inner, table: str, index: int, count: int):
        self._inner = inner
        self._table = table
        self._index = index
        self._count = count

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def snapshot_version(self, table: str):
        """The split share IS part of this wrapper's content identity:
        two tasks of the same fragment on different shares must never
        address one result-cache entry (presto_tpu/cache/ folds this
        token into every key), so the filtered table's token carries
        (index, count) on top of the inner connector's version."""
        from presto_tpu.cache.rules import snapshot_of

        inner = snapshot_of(self._inner, table)
        if inner is None or table != self._table:
            return inner
        return f"{inner}/split{self._index}.{self._count}"

    def splits(self, table: str, target_rows: int):
        splits = self._inner.splits(table, target_rows)
        if table != self._table:
            return splits
        mine = splits[self._index::self._count]
        return mine or [Split(table, 0, 0)]

    def pages(
        self,
        table: str,
        columns: Optional[Sequence[str]] = None,
        target_rows: int = 1 << 20,
        constraint=None,
    ):
        # must re-implement (not delegate): the inner pages() would call
        # the inner splits() and bypass the filter
        splits = self.splits(table, target_rows)
        if constraint:
            splits = self._inner.prune_splits(table, splits, constraint)
        for split in splits:
            if split.row_count:
                yield self._inner.page_for_split(split, columns)


class HashSplitConnector:
    """Hash-repartitioned scans: worker ``index`` of ``count`` sees
    only the rows of each partitioned table whose PARTITION COLUMN
    hashes to it — the DCN realization of the reference's
    hash-repartition exchange (`ExchangeNode(REPARTITION)` →
    `PartitionedOutputOperator` routing rows by hash(key) % n).

    TPU-native divergence (documented): instead of routing serialized
    pages between workers, each worker re-scans and masks — for the
    generator connectors a scan IS a compute (SURVEY §8.2.6
    scan==generate), so "receiving my partition" and "generating my
    partition" are the same device program, with zero DCN page traffic
    between workers. Tables co-partitioned on their join keys make
    every partition-local join a partition of the global join; the
    serde page plane still carries partial states worker→coordinator.
    """

    def __init__(self, inner, partition_cols, index: int, count: int):
        self._inner = inner
        self._partition_cols = dict(partition_cols)  # table -> column
        self._index = index
        self._count = count

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def snapshot_version(self, table: str):
        """Same rule as SplitFilterConnector: a hash-partitioned scan's
        content is (inner content, partition column, index/count) — the
        result-cache token must say so."""
        from presto_tpu.cache.rules import snapshot_of

        inner = snapshot_of(self._inner, table)
        col = self._partition_cols.get(table)
        if inner is None or col is None:
            return inner
        return f"{inner}/hash.{col}.{self._index}.{self._count}"

    def _mask_page(self, page, table: str, columns):
        from presto_tpu.ops import hashing as H
        from presto_tpu.ops import keys as K

        import jax.numpy as jnp

        col = self._partition_cols[table]
        idx = list(columns).index(col)
        blk = page.block(idx)
        cols = K.equality_encoding(blk)
        h = H.hash_columns(cols, [None] * len(cols))
        mine = (h % jnp.uint64(self._count)) == jnp.uint64(self._index)
        if blk.nulls is not None:
            # null keys go to worker 0 so every row lands exactly once
            mine = jnp.where(
                blk.nulls, jnp.uint64(self._index) == jnp.uint64(0),
                mine,
            )
        return page.with_valid(page.valid & mine)

    def pages(
        self,
        table: str,
        columns: Optional[Sequence[str]] = None,
        target_rows: int = 1 << 20,
        constraint=None,
    ):
        part_col = self._partition_cols.get(table)
        if columns is None:
            columns = list(self._inner.table_schema(table).column_names())
        scan_cols = list(columns)
        if part_col is not None and part_col not in scan_cols:
            added = True
            scan_cols.append(part_col)
        else:
            added = False
        splits = self._inner.splits(table, target_rows)
        if constraint:
            splits = self._inner.prune_splits(table, splits, constraint)
        for split in splits:
            if not split.row_count:
                continue
            page = self._inner.page_for_split(split, scan_cols)
            if part_col is not None:
                page = self._mask_page(page, table, scan_cols)
                if added:
                    page = page.select_channels(
                        range(len(scan_cols) - 1))
            yield page
