"""Connector SPI.

Reference: presto-spi spi/connector/* — ConnectorMetadata (schemas),
ConnectorSplitManager (splits), ConnectorPageSourceProvider (pages). The TPU
engine consumes the same three capabilities: describe tables, enumerate row
ranges ("splits"), and produce columnar Pages for a range. Splits are
(start_row, row_count) ranges so a table shards across a device mesh by
simple range partitioning (reference analog: ConnectorSplit streaming to
tasks via SourcePartitionedScheduler).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.page import Page


@dataclasses.dataclass(frozen=True)
class ColumnSchema:
    name: str
    type: T.SqlType


@dataclasses.dataclass(frozen=True)
class TableSchema:
    name: str
    columns: Tuple[ColumnSchema, ...]

    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def column_index(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"no column {name!r} in table {self.name!r}")

    def column_type(self, name: str) -> T.SqlType:
        return self.columns[self.column_index(name)].type


@dataclasses.dataclass(frozen=True)
class Split:
    """A row range of a table (reference: spi/ConnectorSplit)."""

    table: str
    start_row: int
    row_count: int


class Connector:
    """Reference: spi/connector/Connector + ConnectorMetadata."""

    name: str = "connector"

    def tables(self) -> List[str]:
        raise NotImplementedError

    def table_schema(self, table: str) -> TableSchema:
        raise NotImplementedError

    def row_count(self, table: str) -> int:
        raise NotImplementedError

    def splits(self, table: str, target_rows: int) -> List[Split]:
        """Chop the table into row-range splits of ~target_rows each."""
        total = self.row_count(table)
        out = []
        start = 0
        while start < total:
            n = min(target_rows, total - start)
            out.append(Split(table, start, n))
            start += n
        return out or [Split(table, 0, 0)]

    def page_for_split(
        self, split: Split, columns: Optional[Sequence[str]] = None
    ) -> Page:
        raise NotImplementedError

    def gen_body(self, table: str, n: int, names: Tuple[str, ...]):
        """Optional traceable chunk generator for SPMD scans: a pure
        function ``start_row -> (tuple of column arrays, valid mask)`` the
        distributed executor can call inside shard_map so each mesh device
        generates its own split on-device. Return None if the connector
        can only produce host pages (the executor then stages host data
        shard by shard)."""
        return None

    def pages(
        self,
        table: str,
        columns: Optional[Sequence[str]] = None,
        target_rows: int = 1 << 20,
    ) -> Iterator[Page]:
        for split in self.splits(table, target_rows):
            if split.row_count:
                yield self.page_for_split(split, columns)
