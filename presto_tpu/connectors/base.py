"""Connector SPI.

Reference: presto-spi spi/connector/* — ConnectorMetadata (schemas),
ConnectorSplitManager (splits), ConnectorPageSourceProvider (pages). The TPU
engine consumes the same three capabilities: describe tables, enumerate row
ranges ("splits"), and produce columnar Pages for a range. Splits are
(start_row, row_count) ranges so a table shards across a device mesh by
simple range partitioning (reference analog: ConnectorSplit streaming to
tasks via SourcePartitionedScheduler).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.page import Page


@dataclasses.dataclass(frozen=True)
class ColumnSchema:
    name: str
    type: T.SqlType


@dataclasses.dataclass(frozen=True)
class TableSchema:
    name: str
    columns: Tuple[ColumnSchema, ...]

    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def column_index(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"no column {name!r} in table {self.name!r}")

    def column_type(self, name: str) -> T.SqlType:
        return self.columns[self.column_index(name)].type


@dataclasses.dataclass(frozen=True)
class Split:
    """A row range of a table (reference: spi/ConnectorSplit)."""

    table: str
    start_row: int
    row_count: int


class GeneratorConnector:
    """Mixin for on-device deterministic generators (tpch/tpcds): column-
    pruned, jit-compiled chunk generation from the global row index.
    Subclasses provide ``_schemas`` (name -> TableSchema), ``_dicts``
    (table -> column -> Dictionary), a ``_gen_cache`` dict, and one
    ``_gen_<table>(start, n) -> _Lazy`` method per table."""

    def page_for_split(self, split: "Split",
                       columns: Optional[Sequence[str]] = None) -> Page:
        schema = self.table_schema(split.table)
        names = tuple(columns) if columns is not None else tuple(
            schema.column_names()
        )
        fn = self._compiled_gen(split.table, split.row_count, names)
        import jax.numpy as jnp

        datas, valid = fn(
            jnp.int64(split.start_row), jnp.int64(split.row_count)
        )
        dicts = self._dicts.get(split.table, {})
        blocks = []
        from presto_tpu.page import Block

        for nm, data in zip(names, datas):
            blocks.append(
                Block(
                    data=data,
                    type=schema.column_type(nm),
                    nulls=None,
                    dictionary=dicts.get(nm),
                )
            )
        return Page(blocks=tuple(blocks), valid=valid)

    def _compiled_gen(self, table: str, n: int, names: tuple):
        """jit-compiled, column-pruned chunk generator over the CANONICAL
        (ladder-bucketed, exec/shapes.py) chunk shape; start_row and the
        real row count are traced, so one compilation serves every chunk
        whose size lands in the same bucket — tail splits no longer mint
        a program shape per (scale factor, page_rows) combination.
        Generated rows past the real count mask out of `valid` (the
        generators are unbounded past the table end; the dist scan
        relies on the same property)."""
        import jax
        import jax.numpy as jnp

        from presto_tpu.exec import shapes as SH

        n_pad = SH.bucket(n)
        key = (table, n_pad, names)
        if key not in self._gen_cache:
            body = self.gen_body(table, n_pad, names)

            def padded(start, count, _body=body, _n=n_pad):
                datas, valid = _body(start)
                in_range = jnp.arange(_n, dtype=jnp.int64) < count
                return datas, valid & in_range

            self._gen_cache[key] = jax.jit(padded)
        return self._gen_cache[key]

    def _lazy_rows(self, table: str, start, n: int):
        """The table's _Lazy over rows [start, start+n). Tables whose
        generation is elementwise in the row index expose
        ``_gen_<table>_at(idx)`` (any int64 index array); the contiguous
        form derives from it. Tables with slot structure (lineitem)
        keep a dedicated ``_gen_<table>(start, n)``."""
        at = getattr(self, f"_gen_{table}_at", None)
        if at is not None:
            import jax.numpy as jnp

            return at(start + jnp.arange(n, dtype=jnp.int64))
        return getattr(self, f"_gen_{table}")(start, n)

    def gen_body(self, table: str, n: int, names: tuple):
        """Traceable chunk generator (Connector.gen_body): pure function of
        the traced start row, safe inside jit or shard_map."""

        def fn(start):
            lazy = self._lazy_rows(table, start, n)
            return (
                tuple(lazy.get(nm) for nm in names),
                lazy.get("__valid__"),
            )

        return fn

    def gen_at(self, table: str, names: Tuple[str, ...]):
        """Traceable random-access generator (Connector.gen_at): pure
        function of an arbitrary int64 row-index array. Exists exactly
        for tables whose columns are elementwise in the row index
        (``_gen_<table>_at``); None otherwise."""
        at = getattr(self, f"_gen_{table}_at", None)
        if at is None:
            return None

        def fn(idx):
            lazy = at(idx)
            return (
                tuple(lazy.get(nm) for nm in names),
                lazy.get("__valid__"),
            )

        return fn

    def host_rows(self, table: str, target_rows: int = 1 << 20):
        """Materialize a table as Python row tuples (oracle loading)."""
        out = []
        for page in self.pages(table, target_rows=target_rows):
            out.extend(page.to_pylist())
        return out

    # ------------------------------------------------- predicate pushdown
    def monotonic_row_bound(self, table: str, column: str):
        """For a column that is non-decreasing in the row index, return
        f(v) = smallest row index whose value >= v (clamped to >= 0);
        None if the column is not monotonic. Lets prune_splits invert a
        value range into a row range — generator tables get TupleDomain
        pushdown for free on their key columns."""
        return None

    def prune_splits(self, table, splits, constraint):
        out = splits
        for col, lo, hi in constraint:
            f = self.monotonic_row_bound(table, col)
            if f is None:
                continue
            row_lo = max(f(lo), 0) if lo is not None else 0
            row_hi = max(f(hi + 1), 0) if hi is not None else None
            out = [
                s for s in out
                if s.start_row + s.row_count > row_lo
                and (row_hi is None or s.start_row < row_hi)
            ]
        return out


class Connector:
    """Reference: spi/connector/Connector + ConnectorMetadata."""

    name: str = "connector"

    def tables(self) -> List[str]:
        raise NotImplementedError

    def table_schema(self, table: str) -> TableSchema:
        raise NotImplementedError

    def row_count(self, table: str) -> int:
        raise NotImplementedError

    def unique_columns(self, table: str) -> frozenset:
        """Columns whose values are unique across the table (primary
        keys). Metadata the engine may exploit — e.g. the Pallas
        unique-key join fast path (reference analog: connector-provided
        table layouts/constraints consulted by the planner)."""
        return frozenset()

    def snapshot_version(self, table: str) -> Optional[str]:
        """Opaque token that changes whenever the table's CONTENT may
        have changed — the result cache (presto_tpu/cache/) folds it
        into every key, so a write makes stale cached results
        structurally unreachable (reference analog: connector-provided
        table versions consulted for materialized-view staleness).

        Default: derived from the row count, which is exact for the
        immutable deterministic generators (content is a pure function
        of (schema, scale), and scale moves the count). Writable
        connectors MUST override with a token that also moves on
        content-preserving-cardinality writes (UPDATE): the memory
        connector bumps an explicit write counter. Return None when
        staleness cannot be proven — scans of this table then never
        cache."""
        try:
            return f"rows:{self.row_count(table)}"
        except Exception:  # noqa: BLE001 - a connector without counts
            return None    # is simply uncacheable, never a query error

    def splits(self, table: str, target_rows: int) -> List[Split]:
        """Chop the table into row-range splits of ~target_rows each."""
        total = self.row_count(table)
        out = []
        start = 0
        while start < total:
            n = min(target_rows, total - start)
            out.append(Split(table, start, n))
            start += n
        return out or [Split(table, 0, 0)]

    def page_for_split(
        self, split: Split, columns: Optional[Sequence[str]] = None
    ) -> Page:
        raise NotImplementedError

    def prune_splits(
        self, table: str, splits: List[Split], constraint
    ) -> List[Split]:
        """Drop splits that provably contain no row satisfying the pushed
        constraint ((column, lo, hi) closed integer ranges — the
        TupleDomain analog, see exec/pushdown.py). Advisory: the engine
        re-applies the full predicate to surviving pages."""
        return splits

    def gen_body(self, table: str, n: int, names: Tuple[str, ...]):
        """Optional traceable chunk generator for SPMD scans: a pure
        function ``start_row -> (tuple of column arrays, valid mask)`` the
        distributed executor can call inside shard_map so each mesh device
        generates its own split on-device. Return None if the connector
        can only produce host pages (the executor then stages host data
        shard by shard).

        Contract (split-batched execution relies on it): the returned
        function must be traceable under jax.vmap and inside
        jax.lax.scan bodies — pure jnp elementwise in the traced start
        row, no host reads, no python control flow on start — so the
        executor can fold a whole batch of splits into one XLA program
        (exec/executor._fused_stream)."""
        return None

    def gen_batch(self, table: str, n: int, names: Tuple[str, ...]):
        """Optional traceable BATCHED chunk generator: a pure function
        ``starts[int64, B] -> (tuple of [B, n] column arrays,
        valid[B, n])`` generating one n-row chunk per start row in a
        single program — the generation half of split-batched
        execution (exec/executor._fused_stream stacks B splits into a
        [B, n] leading dim and vmaps the fused pipeline body over it).
        Default derives from gen_body via jax.vmap, which the gen_body
        traceability contract guarantees is valid; connectors with a
        cheaper closed batched form may override. None when gen_body
        is None."""
        body = self.gen_body(table, n, names)
        if body is None:
            return None
        import jax

        return jax.vmap(body)

    def gen_at(self, table: str, names: Tuple[str, ...]):
        """Optional traceable RANDOM-ACCESS generator: a pure function
        ``row_idx_array -> (tuple of column arrays, valid mask)`` that
        produces the named columns at arbitrary row indices (clipped to
        the table by the caller). With key_inverse this is what makes a
        join against this table build-free: the executor computes build
        row ids from probe keys arithmetically and GENERATES the carried
        columns at those ids — no hash table, no gathers (the reference's
        LookupJoinOperator collapses to pure compute). None if the table
        cannot be generated at scattered indices."""
        return None

    def key_inverse(self, table: str, column: str):
        """Optional traceable inverse of a unique key column: a pure
        function ``vals -> (row_idx int64 array, found bool array)``
        with the contract that for every value v present in the column,
        ``row_idx`` is the exact table row holding v and found is True;
        for any v not present found is False (row_idx may be anything —
        callers clip before generating). The closed-form analog of the
        reference's LookupSource for deterministic generator tables;
        None when no closed form exists (the engine then builds a real
        hash index)."""
        return None

    def key_window_inverse(self, table: str, column: str):
        """Optional traceable WINDOWED inverse: ``(fn, L)`` where
        ``fn(vals) -> (base_idx, found)`` and every table row whose
        column equals v lies in rows [base_idx, base_idx + L). For
        slot-structured fact tables (ticket/order-major layouts) this
        pins a join key to a small static candidate window; the engine
        resolves the exact row by generating the remaining key columns
        at each of the L candidates (exec/executor: windowed generated
        join). The (column,...) keys tested against the window must
        together be unique per table row. None when the column has no
        window structure."""
        return None

    def pages(
        self,
        table: str,
        columns: Optional[Sequence[str]] = None,
        target_rows: int = 1 << 20,
        constraint=None,
    ) -> Iterator[Page]:
        splits = self.splits(table, target_rows)
        if constraint:
            splits = self.prune_splits(table, splits, constraint)
        for split in splits:
            if split.row_count:
                yield self.page_for_split(split, columns)
