"""Device-resident table cache over any connector.

Reference: presto-memory MemoryPagesStore — pages held resident on the
worker so a scan is a memory read, not a recomputation. The TPU analog
keeps the materialized page list in HBM: the first scan of a (table,
columns, page-size, constraint, snapshot) combination streams and
retains the pages; every later scan re-yields them. Used by the bench
harness to separate "generate the data" from "run the query" (the
reference's benchmarks scan stored tables; our generator connectors
otherwise fuse dbgen-style generation into every scan, SURVEY §8.2.6),
and usable as a session-level table cache for any repeated-scan
workload.

Key discipline (ISSUE 10 fix): constraints are keyed by their
CANONICAL structural encoding (`obs/profile.structural_encode` — the
same identity-free walker the plan fingerprint and result-cache keys
use), never `repr()` — a constraint carrying any non-literal object
would leak object identity/ordering into the key, splitting the cache
on repeats and (worse) colliding across distinct constraints whose
reprs merely match. The inner connector's `snapshot_version` also
rides in the key, so wrapping a WRITABLE connector is safe: a write
moves the token and the stale page list becomes unreachable.
`invalidate(table)` / `drop_cache()` reclaim those bytes eagerly — the
runner's DML path calls them through the result-cache invalidation
hook (runner._invalidate_caches).
"""

from __future__ import annotations

from typing import Optional, Sequence


class CachingConnector:
    """Wraps a connector; delegates everything except pages()."""

    def __init__(self, inner):
        self._inner = inner
        self._page_cache = {}

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _key(self, table, columns, target_rows, constraint):
        """Canonical cache key, or None when the inner connector has
        no snapshot token — the SPI contract (None = staleness cannot
        be proven = never cache) applies to this page cache exactly
        like it applies to the result cache."""
        from presto_tpu.cache.rules import snapshot_of
        from presto_tpu.obs.profile import structural_encode

        snap = snapshot_of(self._inner, table)
        if snap is None:
            return None
        return (
            table,
            tuple(columns) if columns is not None else None,
            target_rows,
            structural_encode(constraint) if constraint else None,
            snap,
        )

    def pages(
        self,
        table: str,
        columns: Optional[Sequence[str]] = None,
        target_rows: int = 1 << 20,
        constraint=None,
    ):
        key = self._key(table, columns, target_rows, constraint)
        if key is None:  # snapshot-less inner: stream through
            return self._inner.pages(table, columns, target_rows,
                                     constraint)
        if key not in self._page_cache:
            self._page_cache[key] = list(
                self._inner.pages(table, columns, target_rows, constraint)
            )
        return iter(self._page_cache[key])

    def gen_body(self, table, n, names):
        """No traceable generation: this connector's whole point is that
        a scan is an HBM read of retained pages. Returning None keeps
        the executor's whole-pipeline fusion (which would regenerate
        inside the fused program and bypass the cache) off this path;
        generated joins (gen_at/key_inverse) still delegate — they are
        lookups, not scans."""
        return None

    def invalidate(self, table: str) -> int:
        """Drop one table's cached page lists (the result-cache
        invalidation path calls this after a write through the
        wrapper; snapshot-keyed entries were already unreachable —
        this frees the HBM now). Returns entries dropped."""
        doomed = [k for k in self._page_cache if k[0] == table]
        for k in doomed:
            del self._page_cache[k]
        return len(doomed)

    def drop_cache(self) -> None:
        self._page_cache.clear()

    @property
    def cached_page_count(self) -> int:
        return sum(len(v) for v in self._page_cache.values())
