"""Device-resident table cache over any connector.

Reference: presto-memory MemoryPagesStore — pages held resident on the
worker so a scan is a memory read, not a recomputation. The TPU analog
keeps the materialized page list in HBM: the first scan of a (table,
columns, page-size, constraint) combination streams and retains the
pages; every later scan re-yields them. Used by the bench harness to
separate "generate the data" from "run the query" (the reference's
benchmarks scan stored tables; our generator connectors otherwise fuse
dbgen-style generation into every scan, SURVEY §8.2.6), and usable as a
session-level table cache for any repeated-scan workload.
"""

from __future__ import annotations

from typing import Optional, Sequence


class CachingConnector:
    """Wraps a connector; delegates everything except pages()."""

    def __init__(self, inner):
        self._inner = inner
        self._page_cache = {}

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def pages(
        self,
        table: str,
        columns: Optional[Sequence[str]] = None,
        target_rows: int = 1 << 20,
        constraint=None,
    ):
        key = (
            table,
            tuple(columns) if columns is not None else None,
            target_rows,
            repr(constraint) if constraint else None,
        )
        if key not in self._page_cache:
            self._page_cache[key] = list(
                self._inner.pages(table, columns, target_rows, constraint)
            )
        return iter(self._page_cache[key])

    def gen_body(self, table, n, names):
        """No traceable generation: this connector's whole point is that
        a scan is an HBM read of retained pages. Returning None keeps
        the executor's whole-pipeline fusion (which would regenerate
        inside the fused program and bypass the cache) off this path;
        generated joins (gen_at/key_inverse) still delegate — they are
        lookups, not scans."""
        return None

    def drop_cache(self) -> None:
        self._page_cache.clear()

    @property
    def cached_page_count(self) -> int:
        return sum(len(v) for v in self._page_cache.values())
