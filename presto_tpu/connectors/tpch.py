"""TPC-H connector: deterministic on-device data generation.

Reference: presto-tpch (TpchConnectorFactory/TpchMetadata/TpchRecordSet,
backed by airlift's Java dbgen port) generates rows on the fly from the row
index — no data files. We keep that killer property and push it further
(SURVEY §8.2.6): every column is a pure function of the global row index,
computed *on device* as a vectorized jax program, so "scan" is "generate" in
HBM and a table shards across a mesh by sharding an iota. Generation is
column-pruned (only requested columns are computed) and jit-compiled per
(table, chunk size, column set).

Determinism & fidelity: structural formulas follow the TPC-H spec / dbgen
semantics exactly where they matter for query behavior —
  - cardinalities (customer 150k·SF, orders 10/customer, 1–7 lineitems,
    partsupp 4/part), sparse orderkeys ((i/8)*32 + i%8 + 1), the
    skip-every-3rd-customer rule for o_custkey,
  - p_retailprice(pk) and l_extendedprice = qty * retailprice(partkey),
    ps_suppkey(pk, i) = (pk + i*(S/4 + (pk-1)/S)) % S + 1 (join-consistent
    across tables), o_totalprice as the exact decimal sum over lineitems,
  - date windows (orderdate 1992-01-01..1998-08-02, ship/commit/receipt
    offsets), returnflag/linestatus derived from CURRENTDATE 1995-06-17,
  - value pools (segments, priorities, ship modes, brands/types/containers,
    the 25 nations / 5 regions and their mapping).
The *randomness* differs: dbgen's per-column Lehmer LCG streams are replaced
by counter-based xxhash64 streams keyed on (table.column, row key). Row
values are therefore deterministic and chunk-independent but not bit-equal
to C dbgen, and free-text fields (names/addresses/comments/phones) draw from
bounded word pools so they stay dictionary-encodable on device. Result
checksums are validated against an independent SQL oracle over the *same*
data (tests run sqlite3), not against dbgen answer sets — documented
divergence from the reference.
"""

from __future__ import annotations

import datetime
import functools
import zlib
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.base import (
    ColumnSchema,
    Connector,
    GeneratorConnector,
    Split,
    TableSchema,
)
from presto_tpu.ops.hashing import xxhash64_u64
from presto_tpu.page import Block, Dictionary, Page

_EPOCH = datetime.date(1970, 1, 1)


def _days(y: int, m: int, d: int) -> int:
    return (datetime.date(y, m, d) - _EPOCH).days


STARTDATE = _days(1992, 1, 1)
ENDDATE = _days(1998, 12, 31)
CURRENTDATE = _days(1995, 6, 17)
ORDERDATE_MAX = ENDDATE - 151

DEC = T.DecimalType(12, 2)

MAX_LINES_PER_ORDER = 7


class PatternDictionary(Dictionary):
    """Virtual dictionary for formatted key strings ('Customer#%09d') —
    decodes lazily so 'Supplier#000000042'-style columns never materialize
    15M strings at SF100 (reference analog: dbgen formats these on the fly).
    Code i maps to value prefix + zero-padded (i + offset); zero-padding
    makes lexicographic order equal numeric order, so sort_rank is the
    identity."""

    def __init__(self, prefix: str, count: int, offset: int = 1,
                 width: int = 9):
        self.prefix = prefix
        self.count = count
        self.offset = offset
        self.width = width
        self._materialized = None
        self._hash = hash(("pattern", prefix, count, offset, width))

    def __len__(self) -> int:
        return self.count

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, PatternDictionary)
            and (self.prefix, self.count, self.offset, self.width)
            == (other.prefix, other.count, other.offset, other.width)
        )

    @property
    def values(self) -> np.ndarray:
        if self._materialized is None:
            self._materialized = np.array(
                [self.prefix + str(i + self.offset).zfill(self.width)
                 for i in range(self.count)],
                dtype=object,
            )
        return self._materialized

    @property
    def _index(self):
        return _PatternIndex(self)

    def code_of(self, value) -> int:
        try:
            s = str(value)
            if not s.startswith(self.prefix):
                return -1
            i = int(s[len(self.prefix):]) - self.offset
            return i if 0 <= i < self.count else -1
        except (ValueError, TypeError):
            return -1

    def decode(self, codes: np.ndarray) -> np.ndarray:
        out = np.empty(codes.shape, dtype=object)
        flat = codes.reshape(-1)
        res = out.reshape(-1)
        for j, c in enumerate(flat):
            c = int(c)
            if 0 <= c < self.count:
                res[j] = self.prefix + str(c + self.offset).zfill(self.width)
            else:
                res[j] = None
        return out

    def sort_rank(self) -> np.ndarray:
        return np.arange(self.count, dtype=np.int32)

    def has_duplicate_values(self) -> bool:
        return False  # unique by construction, never materialize


class _PatternIndex:
    """Mapping-protocol shim so code paths touching dictionary._index keep
    working against PatternDictionary without materialization."""

    def __init__(self, d: PatternDictionary):
        self._d = d

    def get(self, value, default=None):
        c = self._d.code_of(value)
        return default if c < 0 else c

    def __contains__(self, value):
        return self._d.code_of(value) >= 0


# ------------------------------------------------------------- value pools

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIP_MODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
SHIP_INSTRUCT = ["COLLECT COD", "DELIVER IN PERSON", "NONE",
                 "TAKE BACK RETURN"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush "
    "brown burlywood burnished chartreuse chiffon chocolate coral cornflower "
    "cornsilk cream cyan dark deep dim dodger drab firebrick floral forest "
    "frosted gainsboro ghost goldenrod green grey honeydew hot indian ivory "
    "khaki lace lavender lawn lemon light lime linen magenta maroon medium "
    "metallic midnight mint misty moccasin navajo navy olive orange orchid "
    "pale papaya peach peru pink plum powder puff purple red rose rosy royal "
    "saddle salmon sandy seashell sienna sky slate smoke snow spring steel "
    "tan thistle tomato turquoise violet wheat white yellow"
).split()

_COMMENT_A = ("carefully quickly furiously slyly blithely fluffily ruthlessly"
              " boldly daringly evenly silently finally ironically sometimes"
              " never always rarely closely").split()
_COMMENT_B = ("special pending final ironic express regular unusual bold even"
              " silent quick careful idle busy").split()
_COMMENT_C = ("requests deposits accounts packages instructions foxes ideas"
              " theodolites pinto beans dependencies excuses platelets"
              " asymptotes courts dolphins").split()


def _lcg_words(n_entries: int, seed: int, pools: List[List[str]]) -> List[str]:
    """Deterministic host-side word-combination strings (comment pools)."""
    state = seed & 0x7FFFFFFF or 1
    out = []
    for _ in range(n_entries):
        words = []
        for pool in pools:
            state = (state * 48271) % 2147483647
            words.append(pool[state % len(pool)])
        out.append(" ".join(words))
    return out


@functools.lru_cache(maxsize=None)
def _comment_dictionary(n_entries: int, seed: int) -> Dictionary:
    return Dictionary(
        _lcg_words(n_entries, seed,
                   [_COMMENT_A, _COMMENT_B, _COMMENT_C, _COMMENT_A,
                    _COMMENT_C])
    )


@functools.lru_cache(maxsize=None)
def _pname_dictionary(n_entries: int = 4096) -> Dictionary:
    state = 7919
    out = []
    for _ in range(n_entries):
        words = []
        for _ in range(5):
            state = (state * 48271) % 2147483647
            w = COLORS[state % len(COLORS)]
            if w not in words:
                words.append(w)
        out.append(" ".join(words))
    return Dictionary(out)


@functools.lru_cache(maxsize=None)
def _type_dictionary() -> Dictionary:
    return Dictionary(
        [f"{a} {b} {c}" for a in TYPE_S1 for b in TYPE_S2 for c in TYPE_S3]
    )


@functools.lru_cache(maxsize=None)
def _container_dictionary() -> Dictionary:
    return Dictionary(
        [f"{a} {b}" for a in CONTAINER_S1 for b in CONTAINER_S2]
    )


@functools.lru_cache(maxsize=None)
def _brand_dictionary() -> Dictionary:
    return Dictionary(
        [f"Brand#{m}{n}" for m in range(1, 6) for n in range(1, 6)]
    )


@functools.lru_cache(maxsize=None)
def _mfgr_dictionary() -> Dictionary:
    return Dictionary([f"Manufacturer#{m}" for m in range(1, 6)])


_PHONE_LOCALS = 400


@functools.lru_cache(maxsize=None)
def _phone_dictionary() -> Dictionary:
    """code = nation_code*_PHONE_LOCALS + local; country code nation+10."""
    state = 104729
    vals = []
    for nation in range(25):
        cc = nation + 10
        st = state + nation
        for _ in range(_PHONE_LOCALS):
            st = (st * 48271) % 2147483647
            a = 100 + st % 900
            st = (st * 48271) % 2147483647
            b = 100 + st % 900
            st = (st * 48271) % 2147483647
            c = 1000 + st % 9000
            vals.append(f"{cc}-{a}-{b}-{c}")
    return Dictionary(vals)


@functools.lru_cache(maxsize=None)
def _address_dictionary(n_entries: int = 1024) -> Dictionary:
    state = 50021
    vals = []
    alphabet = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 ,"
    for _ in range(n_entries):
        state = (state * 48271) % 2147483647
        ln = 10 + state % 25
        chars = []
        st = state
        for _ in range(ln):
            st = (st * 48271) % 2147483647
            chars.append(alphabet[st % len(alphabet)])
        vals.append("".join(chars))
    return Dictionary(vals)


# --------------------------------------------------------- random streams

def _stream_seed(table: str, column: str) -> int:
    return zlib.crc32(f"tpch.{table}.{column}".encode())


def _draw(keys: jnp.ndarray, table: str, column: str) -> jnp.ndarray:
    """uint64 stream value per key, independent per (table, column)."""
    return xxhash64_u64(
        keys.astype(jnp.uint64), seed=_stream_seed(table, column)
    )


def _unif(keys, table, column, lo: int, hi: int) -> jnp.ndarray:
    """Uniform int64 in [lo, hi] keyed by row key (chunk-independent)."""
    h = _draw(keys, table, column)
    span = jnp.uint64(hi - lo + 1)
    return (h % span).astype(jnp.int64) + jnp.int64(lo)


class _Lazy:
    """Column-pruned generation: entries are thunks evaluated only for the
    requested column set (a traced no-op for the rest). Keeping every
    field lazy matters twice over: pruned scans trace only the touched
    columns, and the generated-join / fused-pipeline kernels that embed
    generation stay small enough to compile quickly (the TPC-DS fact
    value models are ~25 interdependent draws; a windowed join tracing
    them 11x per candidate must pull single fields, not the full
    model)."""

    def __init__(self):
        self._thunks: Dict[str, object] = {}
        self._memo: Dict[str, object] = {}

    def put(self, name: str, thunk):
        self._thunks[name] = thunk

    def get(self, name: str):
        if name not in self._memo:
            self._memo[name] = self._thunks[name]()
        return self._memo[name]

    __getitem__ = get

    def merge(self, other: "_Lazy") -> None:
        """Adopt another lazy's thunks (later put() calls override);
        memoization stays shared through the adopted thunks' own
        closures."""
        self._thunks.update(other._thunks)


# ------------------------------------------------------------- connector


class TpchConnector(GeneratorConnector, Connector):
    """Reference: presto-tpch TpchConnectorFactory — schema name carries the
    scale factor (catalog.sf1.lineitem)."""

    name = "tpch"

    def __init__(self, scale: float = 1.0):
        self.scale = scale
        self.n_customer = max(int(150_000 * scale), 3)
        self.n_orders = self.n_customer * 10
        self.n_part = max(int(200_000 * scale), 4)
        self.n_supplier = max(int(10_000 * scale), 4)
        self.n_partsupp = self.n_part * 4
        self.n_clerk = max(int(1000 * scale), 10)
        self._schemas = _build_schemas()
        self._gen_cache: Dict = {}
        self._dicts = self._build_dictionaries()

    def _build_dictionaries(self) -> Dict[str, Dict[str, Dictionary]]:
        return {
            "region": {
                "r_name": Dictionary(REGIONS),
                "r_comment": _comment_dictionary(512, 11),
            },
            "nation": {
                "n_name": Dictionary([nm for nm, _ in NATIONS]),
                "n_comment": _comment_dictionary(512, 13),
            },
            "part": {
                "p_name": _pname_dictionary(),
                "p_mfgr": _mfgr_dictionary(),
                "p_brand": _brand_dictionary(),
                "p_type": _type_dictionary(),
                "p_container": _container_dictionary(),
                "p_comment": _comment_dictionary(2048, 17),
            },
            "supplier": {
                "s_name": PatternDictionary("Supplier#", self.n_supplier),
                "s_address": _address_dictionary(),
                "s_phone": _phone_dictionary(),
                "s_comment": _comment_dictionary(2048, 19),
            },
            "partsupp": {
                "ps_comment": _comment_dictionary(2048, 23),
            },
            "customer": {
                "c_name": PatternDictionary("Customer#", self.n_customer),
                "c_address": _address_dictionary(),
                "c_phone": _phone_dictionary(),
                "c_mktsegment": Dictionary(SEGMENTS),
                "c_comment": _comment_dictionary(4096, 29),
            },
            "orders": {
                "o_orderstatus": Dictionary(["F", "O", "P"]),
                "o_orderpriority": Dictionary(PRIORITIES),
                "o_clerk": PatternDictionary("Clerk#", self.n_clerk),
                "o_comment": _comment_dictionary(8192, 31),
            },
            "lineitem": {
                "l_returnflag": Dictionary(["A", "R", "N"]),
                "l_linestatus": Dictionary(["F", "O"]),
                "l_shipinstruct": Dictionary(SHIP_INSTRUCT),
                "l_shipmode": Dictionary(SHIP_MODES),
                "l_comment": _comment_dictionary(8192, 37),
            },
        }

    # ------------------------------------------------------------ metadata
    def tables(self) -> List[str]:
        return list(self._schemas)

    def table_schema(self, table: str) -> TableSchema:
        try:
            return self._schemas[table]
        except KeyError:
            raise KeyError(f"tpch has no table {table!r}")

    def row_count(self, table: str) -> int:
        """Slot count for split planning. For lineitem this is the padded
        slot capacity (orders x 7); true cardinality arrives via page
        validity masks — the engine's native representation."""
        return {
            "region": 5,
            "nation": 25,
            "part": self.n_part,
            "supplier": self.n_supplier,
            "partsupp": self.n_partsupp,
            "customer": self.n_customer,
            "orders": self.n_orders,
            "lineitem": self.n_orders * MAX_LINES_PER_ORDER,
        }[table]

    def splits(self, table: str, target_rows: int) -> List[Split]:
        if table == "lineitem":
            # align split boundaries to whole orders (7 slots)
            target_rows = max(
                (target_rows // MAX_LINES_PER_ORDER) * MAX_LINES_PER_ORDER,
                MAX_LINES_PER_ORDER,
            )
        return super().splits(table, target_rows)

    # ----------------------------------------------------------- generation
    # page_for_split/_compiled_gen/gen_body come from GeneratorConnector.

    def unique_columns(self, table: str) -> frozenset:
        return {
            "region": frozenset({"r_regionkey"}),
            "nation": frozenset({"n_nationkey"}),
            "part": frozenset({"p_partkey"}),
            "supplier": frozenset({"s_suppkey"}),
            "customer": frozenset({"c_custkey"}),
            "orders": frozenset({"o_orderkey"}),
        }.get(table, frozenset())

    def monotonic_row_bound(self, table: str, column: str):
        """Key columns are monotonic in the row index (spec layout), so
        pushed key ranges prune whole generator splits (TupleDomain
        pushdown, exec/pushdown.py)."""

        def okey_row(v: int) -> int:
            # smallest order idx with sparse orderkey >= v
            # (okey(i) = (i//8)*32 + i%8 + 1, dbgen mk_sparse)
            v0 = max(v - 1, 0)
            block, w = divmod(v0, 32)
            return block * 8 + min(w, 8)

        return {
            ("orders", "o_orderkey"): okey_row,
            ("lineitem", "l_orderkey"):
                lambda v: okey_row(v) * MAX_LINES_PER_ORDER,
            ("customer", "c_custkey"): lambda v: v - 1,
            ("part", "p_partkey"): lambda v: v - 1,
            ("supplier", "s_suppkey"): lambda v: v - 1,
            ("partsupp", "ps_partkey"): lambda v: (v - 1) * 4,
            ("nation", "n_nationkey"): lambda v: v,
            ("region", "r_regionkey"): lambda v: v,
        }.get((table, column))

    def key_inverse(self, table: str, column: str):
        """Closed-form key->row inverses (Connector.key_inverse): every
        TPC-H key column is an arithmetic function of the row index (spec
        4.2.3 layouts), so the inverse is pure per-element compute —
        the basis of the build-free generated join."""
        n = self.row_count(table) if table in self._schemas else 0

        def dense_from_1(vals):  # key = row + 1
            found = (vals >= 1) & (vals <= n)
            return vals - 1, found

        def dense_from_0(vals):  # key = row
            found = (vals >= 0) & (vals < n)
            return vals, found

        def okey_inv(vals):  # sparse keys, 8 used per 32 (mk_sparse)
            m = vals - 1
            oidx = (m // 32) * 8 + m % 32
            found = (vals >= 1) & (m % 32 < 8) & (oidx < n)
            return oidx, found

        return {
            ("region", "r_regionkey"): dense_from_0,
            ("nation", "n_nationkey"): dense_from_0,
            ("part", "p_partkey"): dense_from_1,
            ("supplier", "s_suppkey"): dense_from_1,
            ("customer", "c_custkey"): dense_from_1,
            ("orders", "o_orderkey"): okey_inv,
        }.get((table, column))

    # ---- per-table generators: return a _Lazy of column thunks over
    # traced global row keys. All values are pure functions of row keys
    # (elementwise in the row-index array — the _at forms serve both
    # contiguous scans and the generated join's random access).

    def _gen_region_at(self, idx) -> _Lazy:
        lz = _Lazy()
        lz.put("r_regionkey", lambda: idx)
        lz.put("r_name", lambda: idx.astype(jnp.int32))
        lz.put("r_comment", lambda: _unif(
            idx, "region", "comment", 0, 511).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_nation_at(self, idx) -> _Lazy:
        region_map = jnp.asarray(
            np.array([r for _, r in NATIONS], dtype=np.int64)
        )
        lz = _Lazy()
        lz.put("n_nationkey", lambda: idx)
        lz.put("n_name", lambda: idx.astype(jnp.int32))
        lz.put("n_regionkey", lambda: region_map[jnp.clip(idx, 0, 24)])
        lz.put("n_comment", lambda: _unif(
            idx, "nation", "comment", 0, 511).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    @staticmethod
    def _retail_price_cents(partkey: jnp.ndarray) -> jnp.ndarray:
        """Spec 4.2.3: (90000 + ((pk/10) mod 20001) + 100*(pk mod 1000))."""
        pk = partkey.astype(jnp.int64)
        return (
            jnp.int64(90000)
            + (pk // 10) % jnp.int64(20001)
            + jnp.int64(100) * (pk % jnp.int64(1000))
        )

    def _gen_part_at(self, idx) -> _Lazy:
        pk = idx + 1
        lz = _Lazy()
        lz.put("p_partkey", lambda: pk)
        lz.put("p_name", lambda: _unif(
            pk, "part", "name", 0, len(_pname_dictionary()) - 1
        ).astype(jnp.int32))
        lz.put("p_mfgr", lambda: _unif(pk, "part", "mfgr", 0, 4)
               .astype(jnp.int32))
        lz.put("p_brand", lambda: (
            _unif(pk, "part", "mfgr", 0, 4) * 5
            + _unif(pk, "part", "brand", 0, 4)
        ).astype(jnp.int32))
        lz.put("p_type", lambda: _unif(
            pk, "part", "type", 0, len(_type_dictionary()) - 1
        ).astype(jnp.int32))
        lz.put("p_size", lambda: _unif(pk, "part", "size", 1, 50)
               .astype(jnp.int32))
        lz.put("p_container", lambda: _unif(
            pk, "part", "container", 0, len(_container_dictionary()) - 1
        ).astype(jnp.int32))
        lz.put("p_retailprice", lambda: self._retail_price_cents(pk))
        lz.put("p_comment", lambda: _unif(
            pk, "part", "comment", 0, 2047).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(pk, dtype=jnp.bool_))
        return lz

    def _gen_supplier_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        nation = lambda: _unif(sk, "supplier", "nationkey", 0, 24)  # noqa
        lz.put("s_suppkey", lambda: sk)
        lz.put("s_name", lambda: (sk - 1).astype(jnp.int32))
        lz.put("s_address", lambda: _unif(
            sk, "supplier", "address", 0, 1023).astype(jnp.int32))
        lz.put("s_nationkey", nation)
        lz.put("s_phone", lambda: (
            nation() * _PHONE_LOCALS
            + _unif(sk, "supplier", "phone", 0, _PHONE_LOCALS - 1)
        ).astype(jnp.int32))
        lz.put("s_acctbal", lambda: _unif(
            sk, "supplier", "acctbal", -99_999, 999_999))
        lz.put("s_comment", lambda: _unif(
            sk, "supplier", "comment", 0, 2047).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(sk, dtype=jnp.bool_))
        return lz

    def _ps_suppkey(self, pk: jnp.ndarray, i: jnp.ndarray) -> jnp.ndarray:
        """Spec 4.2.3 partsupp supplier spread (join-consistent)."""
        S = jnp.int64(self.n_supplier)
        return (pk + i * (S // 4 + (pk - 1) // S)) % S + 1

    def _gen_partsupp_at(self, idx) -> _Lazy:
        pk = idx // 4 + 1
        i = idx % 4
        key = pk * 4 + i
        lz = _Lazy()
        lz.put("ps_partkey", lambda: pk)
        lz.put("ps_suppkey", lambda: self._ps_suppkey(pk, i))
        lz.put("ps_availqty", lambda: _unif(
            key, "partsupp", "availqty", 1, 9999).astype(jnp.int32))
        lz.put("ps_supplycost", lambda: _unif(
            key, "partsupp", "supplycost", 100, 100_000))
        lz.put("ps_comment", lambda: _unif(
            key, "partsupp", "comment", 0, 2047).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(pk, dtype=jnp.bool_))
        return lz

    def _gen_customer_at(self, idx) -> _Lazy:
        ck = idx + 1
        nation = lambda: _unif(ck, "customer", "nationkey", 0, 24)  # noqa
        lz = _Lazy()
        lz.put("c_custkey", lambda: ck)
        lz.put("c_name", lambda: (ck - 1).astype(jnp.int32))
        lz.put("c_address", lambda: _unif(
            ck, "customer", "address", 0, 1023).astype(jnp.int32))
        lz.put("c_nationkey", nation)
        lz.put("c_phone", lambda: (
            nation() * _PHONE_LOCALS
            + _unif(ck, "customer", "phone", 0, _PHONE_LOCALS - 1)
        ).astype(jnp.int32))
        lz.put("c_acctbal", lambda: _unif(
            ck, "customer", "acctbal", -99_999, 999_999))
        lz.put("c_mktsegment", lambda: _unif(
            ck, "customer", "mktsegment", 0, 4).astype(jnp.int32))
        lz.put("c_comment", lambda: _unif(
            ck, "customer", "comment", 0, 4095).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(ck, dtype=jnp.bool_))
        return lz

    # ---- orders + lineitem share per-order line computations

    @staticmethod
    def _orderkey(order_idx: jnp.ndarray) -> jnp.ndarray:
        """Sparse keys, 8 used per 32 (spec 4.2.3 / dbgen mk_sparse)."""
        return (order_idx // 8) * 32 + order_idx % 8 + 1

    def _order_custkey(self, okey: jnp.ndarray) -> jnp.ndarray:
        """Customers whose key % 3 == 0 place no orders (dbgen rule)."""
        n_active = (self.n_customer // 3) * 2
        j = _unif(okey, "orders", "custkey", 0, max(n_active - 1, 0))
        return 3 * (j // 2) + j % 2 + 1

    def _order_date(self, okey: jnp.ndarray) -> jnp.ndarray:
        return _unif(okey, "orders", "orderdate", STARTDATE, ORDERDATE_MAX)

    def _lines_per_order(self, okey: jnp.ndarray) -> jnp.ndarray:
        return _unif(okey, "lineitem", "count", 1, MAX_LINES_PER_ORDER)

    def _line_values(self, okey: jnp.ndarray, line: jnp.ndarray):
        """Per-(order, line) column values; key mixes okey and line number."""
        key = okey * jnp.int64(MAX_LINES_PER_ORDER + 1) + line
        qty = _unif(key, "lineitem", "quantity", 1, 50)
        pk = _unif(key, "lineitem", "partkey", 1, self.n_part)
        supp_i = _unif(key, "lineitem", "suppi", 0, 3)
        disc = _unif(key, "lineitem", "discount", 0, 10)
        tax = _unif(key, "lineitem", "tax", 0, 8)
        odate = self._order_date(okey)
        ship = odate + _unif(key, "lineitem", "shipdate", 1, 121)
        commit = odate + _unif(key, "lineitem", "commitdate", 30, 90)
        receipt = ship + _unif(key, "lineitem", "receiptdate", 1, 30)
        ext = qty * self._retail_price_cents(pk)  # decimal(12,2) cents
        # charge per line at cents precision, round-half-up
        gross = ext * (jnp.int64(100) - disc) * (jnp.int64(100) + tax)
        charge = (gross + jnp.int64(5000)) // jnp.int64(10_000)
        return dict(
            key=key, qty=qty, pk=pk, supp_i=supp_i, disc=disc, tax=tax,
            odate=odate, ship=ship, commit=commit, receipt=receipt, ext=ext,
            charge=charge,
        )

    def _gen_orders_at(self, oidx) -> _Lazy:
        n = oidx.shape[0]
        okey = self._orderkey(oidx)
        lz = _Lazy()

        @functools.lru_cache(maxsize=1)
        def line_matrix():
            # [n, 7] per-line values for totalprice/orderstatus
            line = jnp.arange(1, MAX_LINES_PER_ORDER + 1, dtype=jnp.int64)
            lv = self._line_values(
                jnp.broadcast_to(okey[:, None], (n, MAX_LINES_PER_ORDER)),
                jnp.broadcast_to(line[None, :], (n, MAX_LINES_PER_ORDER)),
            )
            nlines = self._lines_per_order(okey)
            live = line[None, :] <= nlines[:, None]
            return lv, live

        def totalprice():
            lv, live = line_matrix()
            return jnp.sum(jnp.where(live, lv["charge"], 0), axis=1)

        def orderstatus():
            lv, live = line_matrix()
            shipped = lv["ship"] > CURRENTDATE  # linestatus 'O'
            all_o = jnp.all(shipped | ~live, axis=1)
            all_f = jnp.all(~shipped | ~live, axis=1)
            return jnp.where(all_f, 0, jnp.where(all_o, 1, 2)).astype(
                jnp.int32
            )

        lz.put("o_orderkey", lambda: okey)
        lz.put("o_custkey", lambda: self._order_custkey(okey))
        lz.put("o_orderstatus", orderstatus)
        lz.put("o_totalprice", totalprice)
        lz.put("o_orderdate",
               lambda: self._order_date(okey).astype(jnp.int32))
        lz.put("o_orderpriority", lambda: _unif(
            okey, "orders", "priority", 0, 4).astype(jnp.int32))
        lz.put("o_clerk", lambda: _unif(
            okey, "orders", "clerk", 0, self.n_clerk - 1).astype(jnp.int32))
        lz.put("o_shippriority",
               lambda: jnp.zeros_like(okey, dtype=jnp.int32))
        lz.put("o_comment", lambda: _unif(
            okey, "orders", "comment", 0, 8191).astype(jnp.int32))
        lz.put("__valid__",
               lambda: jnp.ones_like(okey, dtype=jnp.bool_))
        return lz

    def _gen_lineitem(self, start, n: int) -> _Lazy:
        slot = start + jnp.arange(n, dtype=jnp.int64)
        oidx = slot // MAX_LINES_PER_ORDER
        line = slot % MAX_LINES_PER_ORDER + 1
        okey = self._orderkey(oidx)
        lz = _Lazy()

        @functools.lru_cache(maxsize=1)
        def lv():
            return self._line_values(okey, line)

        lz.put("l_orderkey", lambda: okey)
        lz.put("l_partkey", lambda: lv()["pk"])
        lz.put("l_suppkey",
               lambda: self._ps_suppkey(lv()["pk"], lv()["supp_i"]))
        lz.put("l_linenumber", lambda: line.astype(jnp.int32))
        lz.put("l_quantity", lambda: lv()["qty"] * jnp.int64(100))
        lz.put("l_extendedprice", lambda: lv()["ext"])
        lz.put("l_discount", lambda: lv()["disc"])
        lz.put("l_tax", lambda: lv()["tax"])
        lz.put("l_returnflag", lambda: jnp.where(
            lv()["receipt"] <= CURRENTDATE,
            _unif(lv()["key"], "lineitem", "rflag", 0, 1),
            2,
        ).astype(jnp.int32))
        lz.put("l_linestatus",
               lambda: (lv()["ship"] > CURRENTDATE).astype(jnp.int32))
        lz.put("l_shipdate", lambda: lv()["ship"].astype(jnp.int32))
        lz.put("l_commitdate", lambda: lv()["commit"].astype(jnp.int32))
        lz.put("l_receiptdate", lambda: lv()["receipt"].astype(jnp.int32))
        lz.put("l_shipinstruct", lambda: _unif(
            lv()["key"], "lineitem", "shipinstruct", 0, 3).astype(jnp.int32))
        lz.put("l_shipmode", lambda: _unif(
            lv()["key"], "lineitem", "shipmode", 0, 6).astype(jnp.int32))
        lz.put("l_comment", lambda: _unif(
            lv()["key"], "lineitem", "comment", 0, 8191).astype(jnp.int32))
        lz.put("__valid__", lambda: line <= self._lines_per_order(okey))
        return lz

def _build_schemas() -> Dict[str, TableSchema]:
    V = T.VARCHAR

    def tbl(name, *cols):
        return TableSchema(
            name, tuple(ColumnSchema(n, t) for n, t in cols)
        )

    return {
        s.name: s
        for s in [
            tbl("region", ("r_regionkey", T.BIGINT), ("r_name", V),
                ("r_comment", V)),
            tbl("nation", ("n_nationkey", T.BIGINT), ("n_name", V),
                ("n_regionkey", T.BIGINT), ("n_comment", V)),
            tbl("part", ("p_partkey", T.BIGINT), ("p_name", V),
                ("p_mfgr", V), ("p_brand", V), ("p_type", V),
                ("p_size", T.INTEGER), ("p_container", V),
                ("p_retailprice", DEC), ("p_comment", V)),
            tbl("supplier", ("s_suppkey", T.BIGINT), ("s_name", V),
                ("s_address", V), ("s_nationkey", T.BIGINT),
                ("s_phone", V), ("s_acctbal", DEC), ("s_comment", V)),
            tbl("partsupp", ("ps_partkey", T.BIGINT),
                ("ps_suppkey", T.BIGINT), ("ps_availqty", T.INTEGER),
                ("ps_supplycost", DEC), ("ps_comment", V)),
            tbl("customer", ("c_custkey", T.BIGINT), ("c_name", V),
                ("c_address", V), ("c_nationkey", T.BIGINT),
                ("c_phone", V), ("c_acctbal", DEC), ("c_mktsegment", V),
                ("c_comment", V)),
            tbl("orders", ("o_orderkey", T.BIGINT), ("o_custkey", T.BIGINT),
                ("o_orderstatus", V), ("o_totalprice", DEC),
                ("o_orderdate", T.DATE), ("o_orderpriority", V),
                ("o_clerk", V), ("o_shippriority", T.INTEGER),
                ("o_comment", V)),
            tbl("lineitem", ("l_orderkey", T.BIGINT),
                ("l_partkey", T.BIGINT), ("l_suppkey", T.BIGINT),
                ("l_linenumber", T.INTEGER), ("l_quantity", DEC),
                ("l_extendedprice", DEC), ("l_discount", DEC),
                ("l_tax", DEC), ("l_returnflag", T.VarcharType(1)),
                ("l_linestatus", T.VarcharType(1)),
                ("l_shipdate", T.DATE), ("l_commitdate", T.DATE),
                ("l_receiptdate", T.DATE), ("l_shipinstruct", V),
                ("l_shipmode", V), ("l_comment", V)),
        ]
    }
