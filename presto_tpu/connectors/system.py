"""System catalog: live engine state queryable as SQL tables.

Reference: presto-main's SystemConnector (system.runtime.queries,
system.runtime.nodes), the information_schema metadata tables, and the
presto-jmx connector's "SQL over the engine's own metrics" — SURVEY
§6.5 names keeping that dogfood loop a build goal. Tables materialize
from registered provider callables at scan time, so every query sees
current state; pages stage host->device exactly like the memory
connector.

Built-in tables (providers wired by LocalRunner / PrestoTpuServer):
  catalogs            catalog_name, connector_name
  tables              table_catalog, table_name
  columns             table_catalog, table_name, column_name,
                      data_type, ordinal_position
  session_properties  name, value, default_value, type, description
  functions           function_name
  runtime_queries     query_id, state, user, query, elapsed_ms,
                      result_rows        (server only)
  nodes               uri, state, is_coordinator (server only)
  metrics             name, value        (server counters)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.connectors.base import (
    ColumnSchema,
    Connector,
    Split,
    TableSchema,
)
from presto_tpu.page import Page


@dataclasses.dataclass(frozen=True)
class _SystemSplit(Split):
    """Split carrying the row snapshot taken at planning time, so every
    scan of one query sees one consistent row set for live tables
    (e.g. runtime_queries) no matter which thread executes it
    (reference: SystemTable cursors materialize per query, not per
    page)."""

    rows: tuple = ()


class SystemConnector(Connector):
    name = "system"

    def __init__(self):
        self._schemas: Dict[str, TableSchema] = {}
        self._providers: Dict[str, Callable[[], List[tuple]]] = {}

    def snapshot_version(self, table: str) -> None:
        """Live provider tables have no staleness token — content can
        change with no cardinality movement (e.g. a query's state
        column), so scans of the system catalog never result-cache
        (cache/rules.py also excludes the catalog by name; this is
        the SPI-level belt to that brace)."""
        return None

    def register(
        self,
        table: str,
        columns: Sequence,
        provider: Callable[[], List[tuple]],
    ) -> None:
        """columns: (name, SqlType) pairs; provider returns current rows
        (reference: SystemTable.cursor building rows per query)."""
        self._schemas[table] = TableSchema(
            table, tuple(ColumnSchema(n, t) for n, t in columns)
        )
        self._providers[table] = provider

    # ---------------------------------------------------------- metadata
    def tables(self) -> List[str]:
        return list(self._schemas)

    def table_schema(self, table: str) -> TableSchema:
        try:
            return self._schemas[table]
        except KeyError:
            raise KeyError(f"system has no table {table!r}")

    def row_count(self, table: str) -> int:
        return max(len(self._providers[table]()), 1)

    def splits(self, table: str, target_rows: int) -> List[Split]:
        """Snapshot the provider ONCE at split planning; the snapshot
        rides on the splits so all page scans of this query agree."""
        rows = tuple(self._providers[table]())
        total = max(len(rows), 1)
        out: List[Split] = []
        start = 0
        while start < total:
            n = min(target_rows, total - start)
            out.append(_SystemSplit(table, start, n, rows=rows))
            start += n
        return out

    # -------------------------------------------------------------- scan
    def page_for_split(
        self, split: Split, columns: Optional[Sequence[str]] = None
    ) -> Page:
        schema = self._schemas[split.table]
        if isinstance(split, _SystemSplit):
            rows = split.rows
        else:  # direct page_for_split callers (tests/tools)
            rows = self._providers[split.table]()
        names = (
            tuple(columns) if columns is not None
            else tuple(schema.column_names())
        )
        lo, hi = split.start_row, split.start_row + split.row_count
        rows = rows[lo:hi]
        cols, types, dicts = [], [], []
        from presto_tpu.page import Dictionary

        for nm in names:
            idx = schema.column_index(nm)
            col = [r[idx] for r in rows]
            t = schema.columns[idx].type
            cols.append(col)
            types.append(t)
            if t.is_dictionary_encoded:
                dicts.append(
                    Dictionary(sorted({v for v in col if v is not None}))
                )
            else:
                dicts.append(None)
        return Page.from_arrays(cols, types, dictionaries=dicts)


def install_standard_tables(sys_conn: SystemConnector, runner) -> None:
    """The metadata tables every engine entry point gets (reference:
    information_schema + SHOW-command backing tables)."""
    V, B = T.VARCHAR, T.BIGINT

    def catalogs():
        return sorted(
            (name, type(conn).__name__)
            for name, conn in runner.catalogs.items()
        )

    def _visible(cat: str, table: str) -> bool:
        """Listings hide tables the user cannot select (reference:
        AccessControl.filterTables/filterColumns over
        information_schema)."""
        from presto_tpu.runner import current_session
        from presto_tpu.security import AccessDeniedError

        session = current_session()
        user = session.user if session else runner.session.user
        try:
            runner.access_control.check_can_select(user, cat, table, ())
        except AccessDeniedError:
            return False
        return True

    def tables():
        out = []
        for cat, conn in sorted(runner.catalogs.items()):
            try:
                for t in conn.tables():
                    if _visible(cat, t):
                        out.append((cat, t))
            except Exception:  # noqa: BLE001 - catalog listings omit
                continue      # broken connectors instead of failing
        return out

    def columns():
        out = []
        for cat, conn in sorted(runner.catalogs.items()):
            try:
                names = conn.tables()
            except Exception:  # noqa: BLE001 - catalog listings omit
                continue      # broken connectors instead of failing
            for t in names:
                if not _visible(cat, t):
                    continue
                schema = conn.table_schema(t)
                for i, c in enumerate(schema.columns):
                    out.append((cat, t, c.name, str(c.type), i + 1))
        return out

    def session_properties():
        # the QUERYING runner's session, not the bootstrap runner's —
        # the server's concurrent path builds a runner per query but
        # shares this connector (reference: session properties are
        # per-session state surfaced by SHOW SESSION)
        from presto_tpu.runner import current_session

        session = current_session() or runner.session
        return session.rows()

    def functions():
        from presto_tpu.expr import functions as F

        return sorted((n,) for n in F.registered_names())

    sys_conn.register(
        "catalogs", [("catalog_name", V), ("connector_name", V)], catalogs
    )
    sys_conn.register(
        "tables", [("table_catalog", V), ("table_name", V)], tables
    )
    sys_conn.register(
        "columns",
        [("table_catalog", V), ("table_name", V), ("column_name", V),
         ("data_type", V), ("ordinal_position", B)],
        columns,
    )
    sys_conn.register(
        "session_properties",
        [("name", V), ("value", V), ("default_value", V), ("type", V),
         ("description", V)],
        session_properties,
    )
    sys_conn.register("functions", [("function_name", V)], functions)
