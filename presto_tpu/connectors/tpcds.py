"""TPC-DS connector: deterministic on-device data generation.

Reference: presto-tpcds (Teradata's Java dsdgen port behind a connector,
SURVEY §3.5) — like presto-tpch, rows are generated on the fly from the row
index, no data files. Same TPU-first design as connectors/tpch.py: every
column is a pure function of the global row index computed on device, so a
table shards across a mesh by sharding an iota, generation is column-pruned
and jit-compiled per (table, chunk, column set).

Scope: the full 24-table census. Store and catalog channels carry the
spec's structural correlations (below); the web channel
(web_sales/web_returns, order-structured like catalog), inventory
(weekly date x item x warehouse cross product), and the remaining
dimensions (warehouse, ship_mode, reason, time_dim, call_center,
catalog_page, web_site, web_page) decode arithmetically or draw from
the same counter-based streams.

Structural fidelity (what query behavior depends on):
  - customer_demographics is the spec's full mixed-radix cross product
    (gender x marital x education x purchase_estimate x credit_rating x
    3 dep counts = 1,920,800 rows); every cd_ column decodes arithmetically
    from cd_demo_sk. household_demographics likewise (20 income bands x
    buy_potential x dep x vehicles = 7,200). income_band is the spec's 20
    fixed bands.
  - date_dim covers 1900-01-01..2100-01-01 (73,049 rows) with
    d_date_sk = 2415022 + day index (the dsdgen Julian-day convention) and
    calendar parts (year/quarter/month/dow) computed on device from the
    day index (Hinnant civil-from-days).
  - store_sales is ticket-structured like lineitem is order-structured:
    a ticket = one (customer, store, date) visit with 1..11 line items;
    slot = ticket * 11 + line with a validity mask, so splits shard on
    whole tickets. store_returns shares the same slot space: a sale slot
    is returned with ~10% probability (spec ratio), the return rides the
    sale's key columns (customer/item/ticket_number), return date 1..90
    days after sale. catalog_sales/catalog_returns mirror this with
    order_number instead of ticket_number.
  - the Q17 behavioral correlation: ~30% of catalog sale lines are
    "re-purchases" — the line copies (bill_customer_sk, item_sk) from a
    returned store sale and is dated after the return. This reproduces the
    store-return->catalog-purchase cross-channel pattern Q17 measures
    (dsdgen achieves it through its own returns model).

Randomness follows the tpch connector's scheme: counter-based xxhash64
streams keyed on (tpcds.table.column, row key) replace dsdgen's per-column
RNG streams. Values are deterministic and chunk-independent but not
bit-equal to C dsdgen; free-text fields draw from bounded pools so they
stay dictionary-encoded on device. Correctness is validated against a SQL
oracle over the *same* generated rows (tests run sqlite3), not dsdgen
answer sets — same documented divergence as connectors/tpch.py.
"""

from __future__ import annotations

import datetime
import functools
import math
import zlib
from typing import Dict, List

import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.connectors.base import (
    ColumnSchema,
    Connector,
    GeneratorConnector,
    Split,
    TableSchema,
)
from presto_tpu.connectors.tpch import (
    COLORS,
    PatternDictionary,
    _Lazy,
    _lcg_words,
)
from presto_tpu.ops.hashing import xxhash64_u64
from presto_tpu.page import Dictionary

_EPOCH = datetime.date(1970, 1, 1)


def _days1900(y: int, m: int, d: int) -> int:
    """Days since 1900-01-01 (the date_dim row index)."""
    return (datetime.date(y, m, d) - datetime.date(1900, 1, 1)).days


# dsdgen convention: d_date_sk of 1900-01-01; sk = JULIAN_BASE + day index
JULIAN_BASE = 2415022
N_DATE = _days1900(2100, 1, 1) + 1  # 73049
# days since unix epoch of 1900-01-01 (negative) — DATE column encoding
_EPOCH_1900 = (datetime.date(1900, 1, 1) - _EPOCH).days

SALES_START = _days1900(1998, 1, 1)
SALES_END = _days1900(2002, 12, 31)

MAX_LINES = 11  # slots per store ticket / catalog order (1..11 live)
SS_RETURN_PCT = 10  # ~10% of store sale lines are returned (spec ratio)
CS_RETURN_PCT = 10
CS_REPURCHASE_PCT = 30  # catalog lines re-purchasing a returned store sale
WS_RETURN_PCT = 10
N_INV_WEEKS = 261  # weekly inventory snapshots over the 5 sales years

DEC72 = T.DecimalType(7, 2)
DEC52 = T.DecimalType(5, 2)


# ------------------------------------------------------------ calendar math

def _civil_from_days(z: jnp.ndarray):
    """days-since-unix-epoch -> (year, month, day); Hinnant's algorithm,
    vectorized int64 (valid across the whole date_dim range)."""
    z = z.astype(jnp.int64) + jnp.int64(719468)
    era = z // jnp.int64(146097)
    doe = z - era * jnp.int64(146097)
    yoe = (
        doe - doe // jnp.int64(1460) + doe // jnp.int64(36524)
        - doe // jnp.int64(146096)
    ) // jnp.int64(365)
    y = yoe + era * jnp.int64(400)
    doy = doe - (jnp.int64(365) * yoe + yoe // jnp.int64(4)
                 - yoe // jnp.int64(100))
    mp = (jnp.int64(5) * doy + jnp.int64(2)) // jnp.int64(153)
    d = doy - (jnp.int64(153) * mp + jnp.int64(2)) // jnp.int64(5) + 1
    m = mp + jnp.int64(3) - jnp.int64(12) * (mp // jnp.int64(10))
    y = y + (mp // jnp.int64(10))
    return y, m, d


# --------------------------------------------------------- random streams

def _stream_seed(table: str, column: str) -> int:
    return zlib.crc32(f"tpcds.{table}.{column}".encode())


def _draw(keys: jnp.ndarray, table: str, column: str) -> jnp.ndarray:
    return xxhash64_u64(
        keys.astype(jnp.uint64), seed=_stream_seed(table, column)
    )


def _unif(keys, table, column, lo: int, hi: int) -> jnp.ndarray:
    """Uniform int64 in [lo, hi] keyed by row key (chunk-independent)."""
    h = _draw(keys, table, column)
    span = jnp.uint64(hi - lo + 1)
    return (h % span).astype(jnp.int64) + jnp.int64(lo)


# ------------------------------------------------------------- value pools

GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"]
CREDIT_RATING = ["Low Risk", "Good", "High Risk", "Unknown"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                 "0-500", "Unknown"]
STATES = ["AL", "CA", "CO", "FL", "GA", "IA", "IL", "IN", "KS", "KY",
          "MI", "MN", "MO", "NC", "NY", "OH", "OK", "TN", "TX", "VA"]
LOCATION_TYPES = ["apartment", "condo", "single family"]
STREET_TYPES = ["Ave", "Blvd", "Court", "Dr", "Lane", "Pkwy", "RD",
                "ST", "Way", "Circle"]
ITEM_SIZES = ["small", "medium", "large", "extra large", "economy",
              "petite", "N/A"]
ITEM_UNITS = ["Each", "Dozen", "Case", "Pallet", "Gross", "Box",
              "Bunch", "Carton", "Cup", "Dram", "Lb", "Oz", "Ton",
              "Tbl", "Tsp", "Unknown"]
# 30-color pool; the first six are Q64's qualification colors so the
# filter keeps a stable ~20% item selectivity at every scale
ITEM_COLORS = ["purple", "burlywood", "indian", "spring", "floral",
               "medium"] + [c for c in COLORS if c not in (
                   "purple", "burlywood", "indian", "spring", "floral",
                   "medium")][:24]
CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
CLASSES = ["accent", "accessories", "archery", "athletic", "baseball",
           "basketball", "bedding", "blinds/shades", "bracelets",
           "camcorders", "camping", "classical", "computers", "consignment",
           "country", "curtains/drapes"]
STORE_NAMES = ["ought", "able", "ese", "anti", "cally", "ation", "eing",
               "n st", "bar", "pri"]
PROMO_NAMES = ["ought", "able", "ese", "anti", "cally", "ation", "eing",
               "n st", "bar", "pri"]
SHIP_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"]
SHIP_CODES = ["AIR", "SURFACE", "SEA"]
SHIP_CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
                 "LATVIAN", "UDEN", "GREAT EASTERN", "DIAMOND", "RUPEKSA",
                 "ORIENTAL", "BOXBUNDLES", "ALLIANCE", "HARMSTORF",
                 "PRIVATECARRIER", "GERMA", "MSC", "BARIAN"]
REASON_DESCS = ["Package was damaged", "Stopped working",
                "Did not get it on time", "Not the product that was "
                "ordred", "Parts missing", "Does not work with a product "
                "that I have", "Gift exchange", "Did not like the color",
                "Did not like the model", "Did not like the make",
                "Did not like the warranty", "No service location in my "
                "area", "Found a better price in a store",
                "Found a better extended warranty in a store",
                "Not working any more", "reason 16", "reason 17",
                "reason 18", "reason 19", "reason 20"]
CC_NAMES = ["NY Metro", "Mid Atlantic", "California", "Pacific Northwest",
            "North Midwest", "Central"]
WP_TYPES = ["ad", "dynamic", "feedback", "general", "order", "protected",
            "welcome"]
AM_PM = ["AM", "PM"]
SHIFTS = ["first", "second", "third"]
SUB_SHIFTS = ["morning", "afternoon", "evening", "night"]
MEAL_TIMES = ["", "breakfast", "lunch", "dinner"]
CC_CLASSES = ["small", "medium", "large"]
CP_TYPES = ["bi-annual", "quarterly", "monthly"]
WEB_NAMES = ["site_0", "site_1", "site_2", "site_3", "site_4", "site_5"]
WEB_COMPANIES = ["pri", "unusual", "able", "ese", "anti", "cally"]
DAY_NAMES = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
             "Saturday", "Sunday"]  # 1900-01-01 was a Monday
HOURS = ["8AM-4PM", "8AM-8PM", "8AM-12AM"]

_WORDS_A = ("pleasant oak cedar elm maple pine walnut sunset lake hill"
            " ridge view park green spring forest river meadow wilson"
            " franklin").split()
_WORDS_B = ("first second third fourth fifth sixth seventh eighth ninth"
            " tenth main center church mill north south east west highland"
            " college").split()


@functools.lru_cache(maxsize=None)
def _word_pool_dictionary(n: int, seed: int) -> Dictionary:
    return Dictionary(_lcg_words(n, seed, [_WORDS_A, _WORDS_B]))


@functools.lru_cache(maxsize=None)
def _desc_dictionary(n: int = 4096) -> Dictionary:
    from presto_tpu.connectors.tpch import _COMMENT_A, _COMMENT_B, _COMMENT_C

    return Dictionary(
        _lcg_words(n, 20260730,
                   [_COMMENT_A, _COMMENT_B, _COMMENT_C, _COMMENT_B,
                    _COMMENT_C, _COMMENT_A, _COMMENT_C])
    )


@functools.lru_cache(maxsize=None)
def _zip_dictionary(n: int = 4096) -> Dictionary:
    state = 60601
    vals = []
    for _ in range(n):
        state = (state * 48271) % 2147483647
        vals.append(str(10000 + state % 89999).zfill(5))
    return Dictionary(vals)


@functools.lru_cache(maxsize=None)
def _street_number_dictionary(n: int = 1000) -> Dictionary:
    return Dictionary([str(i + 1) for i in range(n)])


@functools.lru_cache(maxsize=None)
def _quarter_dictionary() -> Dictionary:
    """code = (year - 1900) * 4 + quarter-1, 1900..2100."""
    return Dictionary(
        [f"{y}Q{q}" for y in range(1900, 2101) for q in (1, 2, 3, 4)]
    )


@functools.lru_cache(maxsize=None)
def _brand_dictionary(n: int = 1000) -> Dictionary:
    return Dictionary([f"Brand#{i + 1}" for i in range(n)])


@functools.lru_cache(maxsize=None)
def _name_dictionary(n: int, seed: int) -> Dictionary:
    pool = ("james mary john patricia robert jennifer michael linda"
            " william elizabeth david barbara richard susan joseph jessica"
            " thomas sarah charles karen lisa nancy betty margaret sandra"
            " ashley dorothy kimberly emily donna michelle carol amanda"
            " melissa deborah stephanie rebecca sharon laura cynthia"
            " kathleen amy shirley angela helen anna brenda pamela nicole"
            " ruth katherine").split()
    state = seed & 0x7FFFFFFF or 1
    out = []
    for _ in range(n):
        state = (state * 48271) % 2147483647
        out.append(pool[state % len(pool)].capitalize())
    return Dictionary(out)


# ------------------------------------------------------------- connector


class TpcdsConnector(GeneratorConnector, Connector):
    """Reference: presto-tpcds TpcdsConnectorFactory — scale factor in the
    schema name (tpcds.sf100)."""

    name = "tpcds"

    def __init__(self, scale: float = 1.0):
        self.scale = scale
        self.n_customer = max(int(100_000 * scale), 200)
        self.n_addr = max(self.n_customer // 2, 100)
        # spec: fixed full cross product; scaled down below SF1 so tiny
        # test fixtures stay tiny (truncation of the same decode)
        self.n_cdemo = (1_920_800 if scale >= 1
                        else max(int(1_920_800 * scale), 1_000))
        self.n_hdemo = 7_200
        self.n_income_band = 20
        self.n_item = max(int(18_000 * math.sqrt(scale)), 100)
        self.n_store = max(int(12 * scale ** 0.75), 2)
        self.n_promo = max(int(300 * scale ** 0.25), 10)
        # 480k tickets x avg 6 live lines = spec's ~2.88M rows at SF1
        self.n_ticket = max(int(480_000 * scale), 64)
        self.n_corder = max(int(240_000 * scale), 32)
        # web channel + remaining dims (round 3: full 24-table census)
        self.n_worder = max(int(120_000 * scale), 16)
        self.n_warehouse = max(int(5 * scale ** 0.5), 2)
        self.n_ship_mode = 20
        self.n_reason = max(int(35 * scale ** 0.25), 5)
        self.n_call_center = max(int(6 * scale ** 0.5), 2)
        self.n_catalog_page = max(int(11_718 * scale ** 0.5), 64)
        self.n_web_site = max(int(30 * scale ** 0.25), 2)
        self.n_web_page = max(int(60 * scale ** 0.5), 4)
        self._schemas = _build_schemas()
        self._gen_cache: Dict = {}
        self._dicts = self._build_dictionaries()

    # ------------------------------------------------------------ metadata
    def tables(self) -> List[str]:
        return list(self._schemas)

    def table_schema(self, table: str) -> TableSchema:
        try:
            return self._schemas[table]
        except KeyError:
            raise KeyError(f"tpcds has no table {table!r}")

    def row_count(self, table: str) -> int:
        """Slot count for split planning; fact-table true cardinality
        arrives via page validity masks (see module docstring)."""
        return {
            "date_dim": N_DATE,
            "item": self.n_item,
            "store": self.n_store,
            "customer": self.n_customer,
            "customer_address": self.n_addr,
            "customer_demographics": self.n_cdemo,
            "household_demographics": self.n_hdemo,
            "income_band": self.n_income_band,
            "promotion": self.n_promo,
            "store_sales": self.n_ticket * MAX_LINES,
            "store_returns": self.n_ticket * MAX_LINES,
            "catalog_sales": self.n_corder * MAX_LINES,
            "catalog_returns": self.n_corder * MAX_LINES,
            "web_sales": self.n_worder * MAX_LINES,
            "web_returns": self.n_worder * MAX_LINES,
            "warehouse": self.n_warehouse,
            "ship_mode": self.n_ship_mode,
            "reason": self.n_reason,
            "time_dim": 86_400,
            "call_center": self.n_call_center,
            "catalog_page": self.n_catalog_page,
            "web_site": self.n_web_site,
            "web_page": self.n_web_page,
            "inventory": N_INV_WEEKS * self.n_item * self.n_warehouse,
        }[table]

    def splits(self, table: str, target_rows: int) -> List[Split]:
        if table in ("store_sales", "store_returns", "catalog_sales",
                     "catalog_returns", "web_sales", "web_returns"):
            # align split boundaries to whole tickets/orders
            target_rows = max(
                (target_rows // MAX_LINES) * MAX_LINES, MAX_LINES
            )
        return super().splits(table, target_rows)

    def unique_columns(self, table: str) -> frozenset:
        return {
            "date_dim": frozenset({"d_date_sk"}),
            "item": frozenset({"i_item_sk"}),
            "store": frozenset({"s_store_sk"}),
            "customer": frozenset({"c_customer_sk"}),
            "customer_address": frozenset({"ca_address_sk"}),
            "customer_demographics": frozenset({"cd_demo_sk"}),
            "household_demographics": frozenset({"hd_demo_sk"}),
            "income_band": frozenset({"ib_income_band_sk"}),
            "promotion": frozenset({"p_promo_sk"}),
            "warehouse": frozenset({"w_warehouse_sk"}),
            "ship_mode": frozenset({"sm_ship_mode_sk"}),
            "reason": frozenset({"r_reason_sk"}),
            "time_dim": frozenset({"t_time_sk"}),
            "call_center": frozenset({"cc_call_center_sk"}),
            "catalog_page": frozenset({"cp_catalog_page_sk"}),
            "web_site": frozenset({"web_site_sk"}),
            "web_page": frozenset({"wp_web_page_sk"}),
        }.get(table, frozenset())

    def monotonic_row_bound(self, table: str, column: str):
        """Surrogate keys are monotonic in the row index, so pushed sk
        ranges prune generator splits (e.g. date_dim filtered to a
        quarter scans ~90 rows, not 73k)."""
        simple = {
            ("date_dim", "d_date_sk"): lambda v: v - JULIAN_BASE,
            ("item", "i_item_sk"): lambda v: v - 1,
            ("store", "s_store_sk"): lambda v: v - 1,
            ("customer", "c_customer_sk"): lambda v: v - 1,
            ("customer_address", "ca_address_sk"): lambda v: v - 1,
            ("customer_demographics", "cd_demo_sk"): lambda v: v - 1,
            ("household_demographics", "hd_demo_sk"): lambda v: v - 1,
            ("income_band", "ib_income_band_sk"): lambda v: v - 1,
            ("promotion", "p_promo_sk"): lambda v: v - 1,
            ("store_sales", "ss_ticket_number"):
                lambda v: (v - 1) * MAX_LINES,
            ("store_returns", "sr_ticket_number"):
                lambda v: (v - 1) * MAX_LINES,
            ("catalog_sales", "cs_order_number"):
                lambda v: (v - 1) * MAX_LINES,
            ("catalog_returns", "cr_order_number"):
                lambda v: (v - 1) * MAX_LINES,
            ("web_sales", "ws_order_number"):
                lambda v: (v - 1) * MAX_LINES,
            ("web_returns", "wr_order_number"):
                lambda v: (v - 1) * MAX_LINES,
            ("warehouse", "w_warehouse_sk"): lambda v: v - 1,
            ("ship_mode", "sm_ship_mode_sk"): lambda v: v - 1,
            ("reason", "r_reason_sk"): lambda v: v - 1,
            ("time_dim", "t_time_sk"): lambda v: v,
            ("call_center", "cc_call_center_sk"): lambda v: v - 1,
            ("catalog_page", "cp_catalog_page_sk"): lambda v: v - 1,
            ("web_site", "web_site_sk"): lambda v: v - 1,
            ("web_page", "wp_web_page_sk"): lambda v: v - 1,
            # inventory is week-major: value = base + 7 * (row // stride)
            ("inventory", "inv_date_sk"): lambda v: (
                -(-(v - JULIAN_BASE - SALES_START) // 7)
                * self.n_item * self.n_warehouse
            ),
        }
        return simple.get((table, column))

    def key_inverse(self, table: str, column: str):
        """Closed-form key->row inverses (Connector.key_inverse): every
        dimension surrogate key is row+1 (or row+JULIAN_BASE for
        date_dim, row for time_dim) — the basis of the build-free
        generated join against the dims."""
        offsets = {
            ("date_dim", "d_date_sk"): JULIAN_BASE,
            ("item", "i_item_sk"): 1,
            ("store", "s_store_sk"): 1,
            ("customer", "c_customer_sk"): 1,
            ("customer_address", "ca_address_sk"): 1,
            ("customer_demographics", "cd_demo_sk"): 1,
            ("household_demographics", "hd_demo_sk"): 1,
            ("income_band", "ib_income_band_sk"): 1,
            ("promotion", "p_promo_sk"): 1,
            ("warehouse", "w_warehouse_sk"): 1,
            ("ship_mode", "sm_ship_mode_sk"): 1,
            ("reason", "r_reason_sk"): 1,
            ("time_dim", "t_time_sk"): 0,
            ("call_center", "cc_call_center_sk"): 1,
            ("catalog_page", "cp_catalog_page_sk"): 1,
            ("web_site", "web_site_sk"): 1,
            ("web_page", "wp_web_page_sk"): 1,
        }
        off = offsets.get((table, column))
        if off is None:
            return None
        n = self.row_count(table)

        def inv(vals, off=off, n=n):
            ridx = vals - off
            return ridx, (ridx >= 0) & (ridx < n)

        return inv

    def key_window_inverse(self, table: str, column: str):
        """Ticket/order numbers pin a fact row to its MAX_LINES-slot
        window (slot = (ticket-1)*MAX_LINES + line): the windowed
        generated join resolves the line by generating the remaining
        key columns at the 11 candidates — even fact⋈fact joins
        (store_sales ⋈ store_returns on ticket+item, Q17/Q64) run
        build-free."""
        tickets = {
            ("store_sales", "ss_ticket_number"): self.n_ticket,
            ("store_returns", "sr_ticket_number"): self.n_ticket,
            ("catalog_sales", "cs_order_number"): self.n_corder,
            ("catalog_returns", "cr_order_number"): self.n_corder,
            ("web_sales", "ws_order_number"): self.n_worder,
            ("web_returns", "wr_order_number"): self.n_worder,
        }
        n = tickets.get((table, column))
        if n is None:
            return None

        def inv(vals, n=n):
            base = (vals - 1) * MAX_LINES
            return base, (vals >= 1) & (vals <= n)

        return inv, MAX_LINES

    def _build_dictionaries(self):
        return {
            "date_dim": {
                "d_date_id": PatternDictionary("D", N_DATE, offset=0),
                "d_day_name": Dictionary(DAY_NAMES),
                "d_quarter_name": _quarter_dictionary(),
                "d_holiday": Dictionary(["N", "Y"]),
                "d_weekend": Dictionary(["N", "Y"]),
            },
            "item": {
                "i_item_id": PatternDictionary("ITEM", self.n_item),
                "i_item_desc": _desc_dictionary(),
                "i_brand": _brand_dictionary(),
                "i_class": Dictionary(CLASSES),
                "i_category": Dictionary(CATEGORIES),
                "i_size": Dictionary(ITEM_SIZES),
                "i_color": Dictionary(ITEM_COLORS),
                "i_units": Dictionary(ITEM_UNITS),
                "i_product_name": _word_pool_dictionary(8192, 41),
            },
            "store": {
                "s_store_id": PatternDictionary("STORE", self.n_store),
                "s_store_name": Dictionary(STORE_NAMES),
                "s_hours": Dictionary(HOURS),
                "s_manager": _name_dictionary(512, 43),
                "s_city": _word_pool_dictionary(1024, 47),
                "s_county": _word_pool_dictionary(64, 53),
                "s_state": Dictionary(STATES),
                "s_zip": _zip_dictionary(),
            },
            "customer": {
                "c_customer_id": PatternDictionary(
                    "CUSTOMER", self.n_customer),
                "c_first_name": _name_dictionary(1024, 59),
                "c_last_name": _name_dictionary(2048, 61),
            },
            "customer_address": {
                "ca_address_id": PatternDictionary("ADDR", self.n_addr),
                "ca_street_number": _street_number_dictionary(),
                "ca_street_name": _word_pool_dictionary(1024, 67),
                "ca_street_type": Dictionary(STREET_TYPES),
                "ca_city": _word_pool_dictionary(1024, 47),
                "ca_county": _word_pool_dictionary(64, 53),
                "ca_state": Dictionary(STATES),
                "ca_zip": _zip_dictionary(),
                "ca_country": Dictionary(["United States"]),
                "ca_location_type": Dictionary(LOCATION_TYPES),
            },
            "customer_demographics": {
                "cd_gender": Dictionary(GENDERS),
                "cd_marital_status": Dictionary(MARITAL),
                "cd_education_status": Dictionary(EDUCATION),
                "cd_credit_rating": Dictionary(CREDIT_RATING),
            },
            "household_demographics": {
                "hd_buy_potential": Dictionary(BUY_POTENTIAL),
            },
            "promotion": {
                "p_promo_id": PatternDictionary("PROMO", self.n_promo),
                "p_promo_name": Dictionary(PROMO_NAMES),
                "p_channel_dmail": Dictionary(["N", "Y"]),
                "p_channel_email": Dictionary(["N", "Y"]),
                "p_channel_tv": Dictionary(["N", "Y"]),
            },
            "warehouse": {
                "w_warehouse_id": PatternDictionary(
                    "WH", self.n_warehouse),
                "w_warehouse_name": _word_pool_dictionary(1024, 71),
                "w_city": _word_pool_dictionary(1024, 47),
                "w_county": _word_pool_dictionary(64, 53),
                "w_state": Dictionary(STATES),
                "w_zip": _zip_dictionary(),
                "w_country": Dictionary(["United States"]),
            },
            "ship_mode": {
                "sm_ship_mode_id": PatternDictionary(
                    "SM", self.n_ship_mode),
                "sm_type": Dictionary(SHIP_TYPES),
                "sm_code": Dictionary(SHIP_CODES),
                "sm_carrier": Dictionary(SHIP_CARRIERS),
                "sm_contract": _word_pool_dictionary(1024, 73),
            },
            "reason": {
                "r_reason_id": PatternDictionary("REASON", self.n_reason),
                "r_reason_desc": Dictionary(REASON_DESCS),
            },
            "time_dim": {
                "t_time_id": PatternDictionary("TIME", 86_400, offset=0),
                "t_am_pm": Dictionary(AM_PM),
                "t_shift": Dictionary(SHIFTS),
                "t_sub_shift": Dictionary(SUB_SHIFTS),
                "t_meal_time": Dictionary(MEAL_TIMES),
            },
            "call_center": {
                "cc_call_center_id": PatternDictionary(
                    "CC", self.n_call_center),
                "cc_name": Dictionary(CC_NAMES),
                "cc_class": Dictionary(CC_CLASSES),
                "cc_hours": Dictionary(HOURS),
                "cc_manager": _name_dictionary(512, 43),
                "cc_county": _word_pool_dictionary(64, 53),
                "cc_state": Dictionary(STATES),
            },
            "catalog_page": {
                "cp_catalog_page_id": PatternDictionary(
                    "CP", self.n_catalog_page),
                "cp_department": Dictionary(["DEPARTMENT"]),
                "cp_description": _desc_dictionary(),
                "cp_type": Dictionary(CP_TYPES),
            },
            "web_site": {
                "web_site_id": PatternDictionary(
                    "WEB", self.n_web_site),
                "web_name": Dictionary(WEB_NAMES),
                "web_manager": _name_dictionary(512, 79),
                "web_company_name": Dictionary(WEB_COMPANIES),
            },
            "web_page": {
                "wp_web_page_id": PatternDictionary(
                    "WP", self.n_web_page),
                "wp_autogen_flag": Dictionary(["N", "Y"]),
                "wp_url": Dictionary(["http://www.foo.com"]),
                "wp_type": Dictionary(WP_TYPES),
            },
        }

    # ------------------------------------------------------ dimension gens

    def _gen_date_dim_at(self, idx) -> _Lazy:
        lz = _Lazy()

        @functools.lru_cache(maxsize=1)
        def ymd():
            return _civil_from_days(idx + jnp.int64(_EPOCH_1900))

        lz.put("d_date_sk", lambda: idx + jnp.int64(JULIAN_BASE))
        lz.put("d_date_id", lambda: idx.astype(jnp.int32))
        lz.put("d_date", lambda: (idx + jnp.int64(_EPOCH_1900))
               .astype(jnp.int32))
        lz.put("d_year", lambda: ymd()[0].astype(jnp.int32))
        lz.put("d_moy", lambda: ymd()[1].astype(jnp.int32))
        lz.put("d_dom", lambda: ymd()[2].astype(jnp.int32))
        lz.put("d_qoy", lambda: ((ymd()[1] - 1) // 3 + 1).astype(jnp.int32))
        lz.put("d_quarter_name", lambda: (
            (ymd()[0] - 1900) * 4 + (ymd()[1] - 1) // 3
        ).astype(jnp.int32))
        lz.put("d_month_seq", lambda: (
            (ymd()[0] - 1900) * 12 + ymd()[1] - 1).astype(jnp.int32))
        lz.put("d_week_seq", lambda: (idx // 7 + 1).astype(jnp.int32))
        lz.put("d_dow", lambda: (idx % 7).astype(jnp.int32))
        lz.put("d_day_name", lambda: (idx % 7).astype(jnp.int32))
        lz.put("d_weekend", lambda: (idx % 7 >= 5).astype(jnp.int32))
        lz.put("d_holiday", lambda: (_unif(
            idx, "date_dim", "holiday", 0, 99) < 5).astype(jnp.int32))
        lz.put("d_fy_year", lambda: ymd()[0].astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_item_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("i_item_sk", lambda: sk)
        lz.put("i_item_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("i_item_desc", lambda: _unif(
            sk, "item", "desc", 0, 4095).astype(jnp.int32))
        # current_price 50.00..89.99: Q64's qualification band (64..79)
        # keeps a stable ~25% selectivity at every scale
        lz.put("i_current_price", lambda: _unif(
            sk, "item", "price", 5000, 8999))
        lz.put("i_wholesale_cost", lambda: _unif(
            sk, "item", "wholesale", 100, 7000))
        lz.put("i_brand_id", lambda: _unif(
            sk, "item", "brand", 1, 1000).astype(jnp.int32))
        lz.put("i_brand", lambda: (
            _unif(sk, "item", "brand", 1, 1000) - 1).astype(jnp.int32))
        lz.put("i_class_id", lambda: _unif(
            sk, "item", "class", 1, len(CLASSES)).astype(jnp.int32))
        lz.put("i_class", lambda: (
            _unif(sk, "item", "class", 1, len(CLASSES)) - 1
        ).astype(jnp.int32))
        lz.put("i_category_id", lambda: _unif(
            sk, "item", "category", 1, len(CATEGORIES)).astype(jnp.int32))
        lz.put("i_category", lambda: (
            _unif(sk, "item", "category", 1, len(CATEGORIES)) - 1
        ).astype(jnp.int32))
        lz.put("i_manufact_id", lambda: _unif(
            sk, "item", "manufact", 1, 1000).astype(jnp.int32))
        lz.put("i_manager_id", lambda: _unif(
            sk, "item", "manager", 1, 100).astype(jnp.int32))
        lz.put("i_size", lambda: _unif(
            sk, "item", "size", 0, len(ITEM_SIZES) - 1).astype(jnp.int32))
        lz.put("i_color", lambda: _unif(
            sk, "item", "color", 0, len(ITEM_COLORS) - 1).astype(jnp.int32))
        lz.put("i_units", lambda: _unif(
            sk, "item", "units", 0, len(ITEM_UNITS) - 1).astype(jnp.int32))
        lz.put("i_product_name", lambda: _unif(
            sk, "item", "pname", 0, 8191).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_store_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("s_store_sk", lambda: sk)
        lz.put("s_store_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("s_store_name", lambda: (
            (sk - 1) % len(STORE_NAMES)).astype(jnp.int32))
        lz.put("s_number_employees", lambda: _unif(
            sk, "store", "employees", 200, 300).astype(jnp.int32))
        lz.put("s_floor_space", lambda: _unif(
            sk, "store", "floor", 5_000_000, 10_000_000).astype(jnp.int32))
        lz.put("s_hours", lambda: _unif(
            sk, "store", "hours", 0, 2).astype(jnp.int32))
        lz.put("s_manager", lambda: _unif(
            sk, "store", "manager", 0, 511).astype(jnp.int32))
        lz.put("s_market_id", lambda: _unif(
            sk, "store", "market", 1, 10).astype(jnp.int32))
        lz.put("s_company_id", lambda: jnp.ones_like(idx, dtype=jnp.int32))
        lz.put("s_city", lambda: _unif(
            sk, "store", "city", 0, 1023).astype(jnp.int32))
        lz.put("s_county", lambda: _unif(
            sk, "store", "county", 0, 63).astype(jnp.int32))
        lz.put("s_state", lambda: _unif(
            sk, "store", "state", 0, len(STATES) - 1).astype(jnp.int32))
        lz.put("s_zip", lambda: _unif(
            sk, "store", "zip", 0, 4095).astype(jnp.int32))
        lz.put("s_gmt_offset", lambda: -jnp.int64(100) * _unif(
            sk, "store", "gmt", 5, 8))
        lz.put("s_tax_precentage", lambda: _unif(
            sk, "store", "tax", 0, 11))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_customer_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()

        def first_sales_day():
            return _unif(sk, "customer", "first_sales",
                         _days1900(1990, 1, 1), _days1900(2002, 1, 1))

        lz.put("c_customer_sk", lambda: sk)
        lz.put("c_customer_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("c_current_cdemo_sk", lambda: _unif(
            sk, "customer", "cdemo", 1, self.n_cdemo))
        lz.put("c_current_hdemo_sk", lambda: _unif(
            sk, "customer", "hdemo", 1, self.n_hdemo))
        lz.put("c_current_addr_sk", lambda: _unif(
            sk, "customer", "addr", 1, self.n_addr))
        lz.put("c_first_sales_date_sk",
               lambda: first_sales_day() + jnp.int64(JULIAN_BASE))
        lz.put("c_first_shipto_date_sk", lambda: (
            first_sales_day()
            + _unif(sk, "customer", "shipto", 0, 120)
            + jnp.int64(JULIAN_BASE)
        ))
        lz.put("c_first_name", lambda: _unif(
            sk, "customer", "fname", 0, 1023).astype(jnp.int32))
        lz.put("c_last_name", lambda: _unif(
            sk, "customer", "lname", 0, 2047).astype(jnp.int32))
        lz.put("c_birth_year", lambda: _unif(
            sk, "customer", "byear", 1924, 1992).astype(jnp.int32))
        lz.put("c_birth_month", lambda: _unif(
            sk, "customer", "bmonth", 1, 12).astype(jnp.int32))
        lz.put("c_birth_day", lambda: _unif(
            sk, "customer", "bday", 1, 28).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_customer_address_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("ca_address_sk", lambda: sk)
        lz.put("ca_address_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("ca_street_number", lambda: _unif(
            sk, "customer_address", "stno", 0, 999).astype(jnp.int32))
        lz.put("ca_street_name", lambda: _unif(
            sk, "customer_address", "stname", 0, 1023).astype(jnp.int32))
        lz.put("ca_street_type", lambda: _unif(
            sk, "customer_address", "sttype", 0, len(STREET_TYPES) - 1
        ).astype(jnp.int32))
        lz.put("ca_city", lambda: _unif(
            sk, "customer_address", "city", 0, 1023).astype(jnp.int32))
        lz.put("ca_county", lambda: _unif(
            sk, "customer_address", "county", 0, 63).astype(jnp.int32))
        lz.put("ca_state", lambda: _unif(
            sk, "customer_address", "state", 0, len(STATES) - 1
        ).astype(jnp.int32))
        lz.put("ca_zip", lambda: _unif(
            sk, "customer_address", "zip", 0, 4095).astype(jnp.int32))
        lz.put("ca_country", lambda: jnp.zeros_like(idx, dtype=jnp.int32))
        lz.put("ca_gmt_offset", lambda: -jnp.int64(100) * _unif(
            sk, "customer_address", "gmt", 5, 8))
        lz.put("ca_location_type", lambda: _unif(
            sk, "customer_address", "loctype", 0, 2).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_customer_demographics_at(self, idx) -> _Lazy:
        """Mixed-radix decode of the spec's full cross product:
        2 x 5 x 7 x 20 x 4 x 7 x 7 x 7 = 1,920,800."""
        sk = idx + 1
        x = sk - 1
        lz = _Lazy()
        gender = x % 2
        x1 = x // 2
        marital = x1 % 5
        x2 = x1 // 5
        edu = x2 % 7
        x3 = x2 // 7
        purch = x3 % 20
        x4 = x3 // 20
        credit = x4 % 4
        x5 = x4 // 4
        dep = x5 % 7
        x6 = x5 // 7
        depemp = x6 % 7
        depcol = (x6 // 7) % 7
        lz.put("cd_demo_sk", lambda: sk)
        lz.put("cd_gender", lambda: gender.astype(jnp.int32))
        lz.put("cd_marital_status", lambda: marital.astype(jnp.int32))
        lz.put("cd_education_status", lambda: edu.astype(jnp.int32))
        lz.put("cd_purchase_estimate",
               lambda: ((purch + 1) * 500).astype(jnp.int32))
        lz.put("cd_credit_rating", lambda: credit.astype(jnp.int32))
        lz.put("cd_dep_count", lambda: dep.astype(jnp.int32))
        lz.put("cd_dep_employed_count", lambda: depemp.astype(jnp.int32))
        lz.put("cd_dep_college_count", lambda: depcol.astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_household_demographics_at(self, idx) -> _Lazy:
        """20 income bands x 6 buy potentials x 10 dep x 6 vehicles."""
        sk = idx + 1
        x = sk - 1
        lz = _Lazy()
        lz.put("hd_demo_sk", lambda: sk)
        lz.put("hd_income_band_sk", lambda: x % 20 + 1)
        lz.put("hd_buy_potential",
               lambda: ((x // 20) % 6).astype(jnp.int32))
        lz.put("hd_dep_count", lambda: ((x // 120) % 10).astype(jnp.int32))
        lz.put("hd_vehicle_count",
               lambda: ((x // 1200) % 6).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_income_band_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("ib_income_band_sk", lambda: sk)
        lz.put("ib_lower_bound", lambda: (
            (sk - 1) * 10_000 + jnp.where(sk > 1, 1, 0)).astype(jnp.int32))
        lz.put("ib_upper_bound", lambda: (sk * 10_000).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_promotion_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("p_promo_sk", lambda: sk)
        lz.put("p_promo_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("p_promo_name", lambda: (
            (sk - 1) % len(PROMO_NAMES)).astype(jnp.int32))
        lz.put("p_cost", lambda: jnp.full_like(idx, 100_000))
        lz.put("p_response_target", lambda: jnp.ones_like(idx, dtype=jnp.int32))
        lz.put("p_channel_dmail", lambda: _unif(
            sk, "promotion", "dmail", 0, 1).astype(jnp.int32))
        lz.put("p_channel_email", lambda: _unif(
            sk, "promotion", "email", 0, 1).astype(jnp.int32))
        lz.put("p_channel_tv", lambda: _unif(
            sk, "promotion", "tv", 0, 1).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    # ----------------------------------------------------- store channel

    def _ticket_values(self, ticket: jnp.ndarray) -> _Lazy:
        """Per-ticket (visit-level) draws shared by every line (LAZY:
        each field traces only when pulled — see _Lazy)."""
        v = _Lazy()
        v.put("customer", lambda: _unif(
            ticket, "store_sales", "customer", 1, self.n_customer))
        v.put("cdemo", lambda: _unif(
            ticket, "store_sales", "cdemo", 1, self.n_cdemo))
        v.put("hdemo", lambda: _unif(
            ticket, "store_sales", "hdemo", 1, self.n_hdemo))
        v.put("addr", lambda: _unif(
            ticket, "store_sales", "addr", 1, self.n_addr))
        v.put("store", lambda: _unif(
            ticket, "store_sales", "store", 1, self.n_store))
        v.put("day", lambda: _unif(
            ticket, "store_sales", "day", SALES_START, SALES_END))
        v.put("nlines", lambda: _unif(
            ticket, "store_sales", "nlines", 1, MAX_LINES))
        return v

    @staticmethod
    def _line_money(stream: str, key: jnp.ndarray) -> _Lazy:
        """The per-line pricing model every sales channel shares
        (wholesale -> markup list price -> discounted sale price -> tax),
        drawn from the channel's own RNG streams. net_paid here has no
        coupon; the store channel overlays its coupon on top."""
        m = _Lazy()
        m.put("qty", lambda: _unif(key, stream, "qty", 1, 100))
        m.put("whole", lambda: _unif(key, stream, "wholesale",
                                     100, 10_000))
        m.put("lst", lambda: (
            m["whole"] * _unif(key, stream, "markup", 100, 300)
            // jnp.int64(100)))
        m.put("sprice", lambda: (
            m["lst"] * (jnp.int64(100) - _unif(key, stream, "disc",
                                               0, 100))
            // jnp.int64(100)))
        m.put("taxp", lambda: _unif(key, stream, "taxp", 0, 9))
        m.put("ext_sales", lambda: m["qty"] * m["sprice"])
        m.put("net_paid", lambda: m["ext_sales"])
        m.put("ext_tax", lambda: (
            m["ext_sales"] * m["taxp"] // jnp.int64(100)))
        return m

    def _ss_values(self, slot: jnp.ndarray) -> _Lazy:
        """Per-slot store_sales values: pure functions of the global slot
        index (ticket * MAX_LINES + line-1); shared by store_returns and
        the catalog re-purchase correlation."""
        ticket = slot // MAX_LINES
        line = slot % MAX_LINES + 1
        tv = self._ticket_values(ticket)
        key = slot
        m = self._line_money("store_sales", key)
        v = _Lazy()
        v.merge(m)
        v.merge(tv)
        v.put("ticket", lambda: ticket)
        v.put("line", lambda: line)
        v.put("key", lambda: key)
        v.put("coupon", lambda: jnp.where(
            _unif(key, "store_sales", "hascoup", 0, 9) < 2,
            m["ext_sales"] * _unif(key, "store_sales", "cfrac", 0, 50)
            // 100,
            0,
        ))
        # store channel overlays the coupon on the shared money model
        v.put("net_paid", lambda: m["ext_sales"] - v["coupon"])
        v.put("ext_tax", lambda: (
            v["net_paid"] * m["taxp"] // jnp.int64(100)))
        v.put("valid", lambda: line <= tv["nlines"])
        v.put("returned", lambda: v["valid"] & (
            _unif(key, "store_returns", "flag", 0, 99) < SS_RETURN_PCT
        ))
        # items within a ticket are DISTINCT (dsdgen picks store-order
        # items from a permutation): base + line*stride mod n_item with
        # stride < n_item/MAX_LINES guarantees the 11 lines collide
        # never — and (ss_ticket_number, ss_item_sk) is a true key,
        # which the windowed generated join relies on
        v.put("item", lambda: (
            _unif(ticket, "store_sales", "itembase", 0, self.n_item - 1)
            + line * (1 + _unif(
                ticket, "store_sales", "itemstride", 0,
                max(self.n_item // (MAX_LINES + 1) - 1, 0)))
        ) % self.n_item + 1)
        v.put("promo", lambda: _unif(
            key, "store_sales", "promo", 1, self.n_promo))
        return v

    def _gen_store_sales_at(self, idx) -> _Lazy:
        slot = idx
        lz = _Lazy()

        @functools.lru_cache(maxsize=1)
        def sv():
            return self._ss_values(slot)

        lz.put("ss_sold_date_sk",
               lambda: sv()["day"] + jnp.int64(JULIAN_BASE))
        lz.put("ss_sold_time_sk", lambda: _unif(
            slot, "store_sales", "time", 28800, 75600))
        lz.put("ss_item_sk", lambda: sv()["item"])
        lz.put("ss_customer_sk", lambda: sv()["customer"])
        lz.put("ss_cdemo_sk", lambda: sv()["cdemo"])
        lz.put("ss_hdemo_sk", lambda: sv()["hdemo"])
        lz.put("ss_addr_sk", lambda: sv()["addr"])
        lz.put("ss_store_sk", lambda: sv()["store"])
        lz.put("ss_promo_sk", lambda: sv()["promo"])
        lz.put("ss_ticket_number", lambda: sv()["ticket"] + 1)
        lz.put("ss_quantity", lambda: sv()["qty"].astype(jnp.int32))
        lz.put("ss_wholesale_cost", lambda: sv()["whole"])
        lz.put("ss_list_price", lambda: sv()["lst"])
        lz.put("ss_sales_price", lambda: sv()["sprice"])
        lz.put("ss_ext_discount_amt",
               lambda: sv()["qty"] * (sv()["lst"] - sv()["sprice"]))
        lz.put("ss_ext_sales_price", lambda: sv()["ext_sales"])
        lz.put("ss_ext_wholesale_cost",
               lambda: sv()["qty"] * sv()["whole"])
        lz.put("ss_ext_list_price", lambda: sv()["qty"] * sv()["lst"])
        lz.put("ss_ext_tax", lambda: sv()["ext_tax"])
        lz.put("ss_coupon_amt", lambda: sv()["coupon"])
        lz.put("ss_net_paid", lambda: sv()["net_paid"])
        lz.put("ss_net_paid_inc_tax",
               lambda: sv()["net_paid"] + sv()["ext_tax"])
        lz.put("ss_net_profit", lambda: (
            sv()["net_paid"] - sv()["qty"] * sv()["whole"]))
        lz.put("__valid__", lambda: sv()["valid"])
        return lz

    @staticmethod
    def _return_money(stream: str, key, sv: _Lazy) -> _Lazy:
        """Shared return-line money model for both channels: quantity,
        amount/tax, and the refunded/reversed/store-credit split of the
        amount (stream names the RNG streams so the channels differ).
        sv supplies the sale line's qty/sprice/taxp/day lazily."""
        rv = _Lazy()
        rv.put("rqty", lambda: (
            _unif(key, stream, "qty", 1, 100) % sv["qty"] + 1))
        rv.put("ramt", lambda: rv["rqty"] * sv["sprice"])
        rv.put("rtax", lambda: (
            rv["ramt"] * sv["taxp"] // jnp.int64(100)))
        rv.put("refunded", lambda: (
            rv["ramt"] * _unif(key, stream, "reffrac", 0, 100)
            // jnp.int64(100)))
        rv.put("reversed_c", lambda: (
            (rv["ramt"] - rv["refunded"])
            * _unif(key, stream, "revfrac", 0, 100) // jnp.int64(100)))
        rv.put("credit", lambda: (
            rv["ramt"] - rv["refunded"] - rv["reversed_c"]))
        rv.put("fee", lambda: _unif(key, stream, "fee", 100, 10_000))
        rv.put("ship", lambda: _unif(key, stream, "ship", 0, 5_000))
        rv.put("rday", lambda: (
            sv["day"] + _unif(key, stream, "lag", 1, 90)))
        return rv

    def _sr_values(self, slot: jnp.ndarray) -> _Lazy:
        sv = self._ss_values(slot)
        rv = self._return_money("store_returns", slot, sv)
        rv.put("sv", lambda: sv)
        return rv

    def _gen_store_returns_at(self, idx) -> _Lazy:
        slot = idx
        lz = _Lazy()

        @functools.lru_cache(maxsize=1)
        def rv():
            return self._sr_values(slot)

        def sv():
            return rv()["sv"]

        lz.put("sr_returned_date_sk",
               lambda: rv()["rday"] + jnp.int64(JULIAN_BASE))
        lz.put("sr_return_time_sk", lambda: _unif(
            slot, "store_returns", "time", 28800, 75600))
        lz.put("sr_item_sk", lambda: sv()["item"])
        lz.put("sr_customer_sk", lambda: sv()["customer"])
        lz.put("sr_cdemo_sk", lambda: sv()["cdemo"])
        lz.put("sr_hdemo_sk", lambda: sv()["hdemo"])
        lz.put("sr_addr_sk", lambda: sv()["addr"])
        lz.put("sr_store_sk", lambda: sv()["store"])
        lz.put("sr_reason_sk", lambda: _unif(
            slot, "store_returns", "reason", 1, self.n_reason))
        lz.put("sr_ticket_number", lambda: sv()["ticket"] + 1)
        lz.put("sr_return_quantity",
               lambda: rv()["rqty"].astype(jnp.int32))
        lz.put("sr_return_amt", lambda: rv()["ramt"])
        lz.put("sr_return_tax", lambda: rv()["rtax"])
        lz.put("sr_return_amt_inc_tax",
               lambda: rv()["ramt"] + rv()["rtax"])
        lz.put("sr_fee", lambda: rv()["fee"])
        lz.put("sr_return_ship_cost", lambda: rv()["ship"])
        lz.put("sr_refunded_cash", lambda: rv()["refunded"])
        lz.put("sr_reversed_charge", lambda: rv()["reversed_c"])
        lz.put("sr_store_credit", lambda: rv()["credit"])
        lz.put("sr_net_loss", lambda: (
            rv()["fee"] + rv()["ship"] + rv()["rtax"]))
        lz.put("__valid__", lambda: sv()["returned"])
        return lz

    # ---------------------------------------------------- catalog channel

    def _cs_values(self, slot: jnp.ndarray) -> _Lazy:
        """Per-slot catalog_sales values. The re-purchase correlation: a
        line targets a pseudo-random store-sales slot; when that slot is a
        returned sale (and this line drew the 30% correlation), the line
        copies the return's (customer, item) and is dated after it."""
        order = slot // MAX_LINES
        line = slot % MAX_LINES + 1
        key = slot
        m = self._line_money("catalog_sales", key)
        v = _Lazy()
        v.merge(m)
        v.put("order", lambda: order)
        v.put("line", lambda: line)
        v.put("key", lambda: key)
        v.put("valid", lambda: line <= _unif(
            order, "catalog_sales", "nlines", 1, MAX_LINES))
        # correlation target: a returned store sale re-purchased by
        # catalog; pure function of the target slot index
        n_ss = self.n_ticket * MAX_LINES

        def t_vals():
            t_slot = _unif(key, "catalog_sales", "corrslot", 0, n_ss - 1)
            return self._sr_values(t_slot)

        v.put("_t", t_vals)
        v.put("corr", lambda: v["valid"] & v["_t"]["sv"]["returned"] & (
            _unif(key, "catalog_sales", "corr", 0, 99)
            < CS_REPURCHASE_PCT
        ))
        v.put("customer", lambda: jnp.where(
            v["corr"], v["_t"]["sv"]["customer"],
            _unif(order, "catalog_sales", "customer",
                  1, self.n_customer),
        ))
        v.put("item", lambda: jnp.where(
            v["corr"], v["_t"]["sv"]["item"],
            _unif(key, "catalog_sales", "item", 1, self.n_item),
        ))
        v.put("day", lambda: jnp.clip(
            jnp.where(
                v["corr"],
                v["_t"]["rday"] + _unif(key, "catalog_sales",
                                        "lag", 1, 60),
                _unif(order, "catalog_sales", "day",
                      SALES_START, SALES_END),
            ),
            SALES_START, SALES_END,
        ))
        v.put("returned", lambda: v["valid"] & (
            _unif(key, "catalog_returns", "flag", 0, 99) < CS_RETURN_PCT
        ))
        v.put("cdemo", lambda: _unif(
            order, "catalog_sales", "cdemo", 1, self.n_cdemo))
        v.put("hdemo", lambda: _unif(
            order, "catalog_sales", "hdemo", 1, self.n_hdemo))
        v.put("addr", lambda: _unif(
            order, "catalog_sales", "addr", 1, self.n_addr))
        v.put("promo", lambda: _unif(
            key, "catalog_sales", "promo", 1, self.n_promo))
        return v

    def _gen_catalog_sales_at(self, idx) -> _Lazy:
        slot = idx
        lz = _Lazy()

        @functools.lru_cache(maxsize=1)
        def cv():
            return self._cs_values(slot)

        lz.put("cs_sold_date_sk",
               lambda: cv()["day"] + jnp.int64(JULIAN_BASE))
        lz.put("cs_ship_date_sk", lambda: (
            cv()["day"] + _unif(slot, "catalog_sales", "shiplag", 2, 30)
            + jnp.int64(JULIAN_BASE)))
        lz.put("cs_bill_customer_sk", lambda: cv()["customer"])
        lz.put("cs_bill_cdemo_sk", lambda: cv()["cdemo"])
        lz.put("cs_bill_hdemo_sk", lambda: cv()["hdemo"])
        lz.put("cs_bill_addr_sk", lambda: cv()["addr"])
        lz.put("cs_ship_customer_sk", lambda: cv()["customer"])
        lz.put("cs_ship_addr_sk", lambda: cv()["addr"])
        lz.put("cs_item_sk", lambda: cv()["item"])
        lz.put("cs_promo_sk", lambda: cv()["promo"])
        lz.put("cs_order_number", lambda: cv()["order"] + 1)
        lz.put("cs_quantity", lambda: cv()["qty"].astype(jnp.int32))
        lz.put("cs_wholesale_cost", lambda: cv()["whole"])
        lz.put("cs_list_price", lambda: cv()["lst"])
        lz.put("cs_sales_price", lambda: cv()["sprice"])
        lz.put("cs_ext_discount_amt",
               lambda: cv()["qty"] * (cv()["lst"] - cv()["sprice"]))
        lz.put("cs_ext_sales_price", lambda: cv()["ext_sales"])
        lz.put("cs_ext_wholesale_cost",
               lambda: cv()["qty"] * cv()["whole"])
        lz.put("cs_ext_list_price", lambda: cv()["qty"] * cv()["lst"])
        lz.put("cs_ext_tax", lambda: cv()["ext_tax"])
        lz.put("cs_coupon_amt", lambda: jnp.zeros_like(idx, dtype=jnp.int64))
        lz.put("cs_ext_ship_cost", lambda: _unif(
            slot, "catalog_sales", "shipcost", 0, 5_000))
        lz.put("cs_net_paid", lambda: cv()["net_paid"])
        lz.put("cs_net_paid_inc_tax",
               lambda: cv()["net_paid"] + cv()["ext_tax"])
        lz.put("cs_net_profit", lambda: (
            cv()["net_paid"] - cv()["qty"] * cv()["whole"]))
        lz.put("__valid__", lambda: cv()["valid"])
        return lz

    def _gen_catalog_returns_at(self, idx) -> _Lazy:
        slot = idx
        lz = _Lazy()

        @functools.lru_cache(maxsize=1)
        def cv():
            return self._cs_values(slot)

        @functools.lru_cache(maxsize=1)
        def rv():
            c = cv()
            return self._return_money("catalog_returns", c["key"], c)

        lz.put("cr_returned_date_sk",
               lambda: rv()["rday"] + jnp.int64(JULIAN_BASE))
        lz.put("cr_item_sk", lambda: cv()["item"])
        lz.put("cr_refunded_customer_sk", lambda: cv()["customer"])
        lz.put("cr_returning_customer_sk", lambda: cv()["customer"])
        lz.put("cr_order_number", lambda: cv()["order"] + 1)
        lz.put("cr_return_quantity",
               lambda: rv()["rqty"].astype(jnp.int32))
        lz.put("cr_return_amount", lambda: rv()["ramt"])
        lz.put("cr_return_tax", lambda: rv()["rtax"])
        lz.put("cr_return_amt_inc_tax",
               lambda: rv()["ramt"] + rv()["rtax"])
        lz.put("cr_fee", lambda: rv()["fee"])
        lz.put("cr_return_ship_cost", lambda: rv()["ship"])
        lz.put("cr_refunded_cash", lambda: rv()["refunded"])
        lz.put("cr_reversed_charge", lambda: rv()["reversed_c"])
        lz.put("cr_store_credit", lambda: rv()["credit"])
        lz.put("cr_net_loss", lambda: (
            rv()["fee"] + rv()["ship"] + rv()["rtax"]))
        lz.put("cr_reason_sk", lambda: _unif(
            cv()["key"], "catalog_returns", "reason", 1, self.n_reason))
        lz.put("__valid__", lambda: cv()["returned"])
        return lz

    # ------------------------------------------------- remaining dims
    # (round 3: the 24-table census — web channel, inventory, and the
    # small dimensions the long-tail queries touch)

    def _gen_warehouse_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("w_warehouse_sk", lambda: sk)
        lz.put("w_warehouse_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("w_warehouse_name", lambda: _unif(
            sk, "warehouse", "name", 0, 1023).astype(jnp.int32))
        lz.put("w_warehouse_sq_ft", lambda: _unif(
            sk, "warehouse", "sqft", 50_000, 1_000_000).astype(jnp.int32))
        lz.put("w_city", lambda: _unif(
            sk, "warehouse", "city", 0, 1023).astype(jnp.int32))
        lz.put("w_county", lambda: _unif(
            sk, "warehouse", "county", 0, 63).astype(jnp.int32))
        lz.put("w_state", lambda: _unif(
            sk, "warehouse", "state", 0, len(STATES) - 1
        ).astype(jnp.int32))
        lz.put("w_zip", lambda: _unif(
            sk, "warehouse", "zip", 0, 4095).astype(jnp.int32))
        lz.put("w_country", lambda: jnp.zeros_like(idx, dtype=jnp.int32))
        lz.put("w_gmt_offset", lambda: -jnp.int64(100) * _unif(
            sk, "warehouse", "gmt", 5, 8))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_ship_mode_at(self, idx) -> _Lazy:
        sk = idx + 1
        x = sk - 1
        lz = _Lazy()
        lz.put("sm_ship_mode_sk", lambda: sk)
        lz.put("sm_ship_mode_id", lambda: x.astype(jnp.int32))
        lz.put("sm_type", lambda: (
            x % len(SHIP_TYPES)).astype(jnp.int32))
        lz.put("sm_code", lambda: (
            (x // len(SHIP_TYPES)) % len(SHIP_CODES)).astype(jnp.int32))
        lz.put("sm_carrier", lambda: (
            x % len(SHIP_CARRIERS)).astype(jnp.int32))
        lz.put("sm_contract", lambda: _unif(
            sk, "ship_mode", "contract", 0, 1023).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_reason_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("r_reason_sk", lambda: sk)
        lz.put("r_reason_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("r_reason_desc", lambda: (
            (sk - 1) % len(REASON_DESCS)).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_time_dim_at(self, idx) -> _Lazy:
        """86,400 rows, one per second of day; every column decodes
        arithmetically from t_time_sk (like date_dim from the day
        index)."""
        sk = idx
        hour = sk // 3600
        lz = _Lazy()
        lz.put("t_time_sk", lambda: sk)
        lz.put("t_time_id", lambda: sk.astype(jnp.int32))
        lz.put("t_time", lambda: sk.astype(jnp.int32))
        lz.put("t_hour", lambda: hour.astype(jnp.int32))
        lz.put("t_minute", lambda: ((sk // 60) % 60).astype(jnp.int32))
        lz.put("t_second", lambda: (sk % 60).astype(jnp.int32))
        lz.put("t_am_pm", lambda: (hour >= 12).astype(jnp.int32))
        lz.put("t_shift", lambda: (hour // 8).astype(jnp.int32))
        lz.put("t_sub_shift", lambda: jnp.clip(
            (hour - 4) // 6, 0, 3).astype(jnp.int32))
        lz.put("t_meal_time", lambda: jnp.where(
            (hour >= 6) & (hour <= 8), 1,
            jnp.where((hour >= 11) & (hour <= 13), 2,
                      jnp.where((hour >= 17) & (hour <= 19), 3, 0)),
        ).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_call_center_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("cc_call_center_sk", lambda: sk)
        lz.put("cc_call_center_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("cc_name", lambda: (
            (sk - 1) % len(CC_NAMES)).astype(jnp.int32))
        lz.put("cc_class", lambda: (
            (sk - 1) % 3).astype(jnp.int32))
        lz.put("cc_employees", lambda: _unif(
            sk, "call_center", "emp", 10, 700).astype(jnp.int32))
        lz.put("cc_sq_ft", lambda: _unif(
            sk, "call_center", "sqft", 1_000, 50_000).astype(jnp.int32))
        lz.put("cc_hours", lambda: (
            (sk - 1) % len(HOURS)).astype(jnp.int32))
        lz.put("cc_manager", lambda: _unif(
            sk, "call_center", "mgr", 0, 511).astype(jnp.int32))
        lz.put("cc_market_id", lambda: _unif(
            sk, "call_center", "mkt", 1, 6).astype(jnp.int32))
        lz.put("cc_county", lambda: _unif(
            sk, "call_center", "county", 0, 63).astype(jnp.int32))
        lz.put("cc_state", lambda: _unif(
            sk, "call_center", "state", 0, len(STATES) - 1
        ).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_catalog_page_at(self, idx) -> _Lazy:
        sk = idx + 1
        pages_per_cat = 108  # spec: ~108 pages per catalog number
        lz = _Lazy()
        lz.put("cp_catalog_page_sk", lambda: sk)
        lz.put("cp_catalog_page_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("cp_start_date_sk", lambda: jnp.int64(JULIAN_BASE)
               + SALES_START + ((sk - 1) // pages_per_cat) * 30)
        lz.put("cp_end_date_sk", lambda: jnp.int64(JULIAN_BASE)
               + SALES_START + ((sk - 1) // pages_per_cat) * 30 + 90)
        lz.put("cp_department", lambda: jnp.zeros_like(idx, dtype=jnp.int32))
        lz.put("cp_catalog_number", lambda: (
            (sk - 1) // pages_per_cat + 1).astype(jnp.int32))
        lz.put("cp_catalog_page_number", lambda: (
            (sk - 1) % pages_per_cat + 1).astype(jnp.int32))
        lz.put("cp_description", lambda: _unif(
            sk, "catalog_page", "desc", 0, 4095).astype(jnp.int32))
        lz.put("cp_type", lambda: (
            (sk - 1) % 3).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_web_site_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("web_site_sk", lambda: sk)
        lz.put("web_site_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("web_name", lambda: (
            (sk - 1) % 6).astype(jnp.int32))
        lz.put("web_open_date_sk", lambda: jnp.int64(JULIAN_BASE)
               + SALES_START - _unif(sk, "web_site", "open", 100, 2000))
        lz.put("web_manager", lambda: _unif(
            sk, "web_site", "mgr", 0, 511).astype(jnp.int32))
        lz.put("web_market_id", lambda: _unif(
            sk, "web_site", "mkt", 1, 6).astype(jnp.int32))
        lz.put("web_company_id", lambda: (
            (sk - 1) % 6 + 1).astype(jnp.int32))
        lz.put("web_company_name", lambda: (
            (sk - 1) % 6).astype(jnp.int32))
        lz.put("web_gmt_offset", lambda: -jnp.int64(100) * _unif(
            sk, "web_site", "gmt", 5, 8))
        lz.put("web_tax_percentage", lambda: _unif(
            sk, "web_site", "tax", 0, 12))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_web_page_at(self, idx) -> _Lazy:
        sk = idx + 1
        lz = _Lazy()
        lz.put("wp_web_page_sk", lambda: sk)
        lz.put("wp_web_page_id", lambda: (sk - 1).astype(jnp.int32))
        lz.put("wp_creation_date_sk", lambda: jnp.int64(JULIAN_BASE)
               + SALES_START - _unif(sk, "web_page", "created", 1, 1000))
        lz.put("wp_access_date_sk", lambda: jnp.int64(JULIAN_BASE)
               + SALES_START + _unif(sk, "web_page", "access", 0, 100))
        lz.put("wp_autogen_flag", lambda: _unif(
            sk, "web_page", "autogen", 0, 1).astype(jnp.int32))
        lz.put("wp_customer_sk", lambda: _unif(
            sk, "web_page", "cust", 1, self.n_customer))
        lz.put("wp_url", lambda: jnp.zeros_like(idx, dtype=jnp.int32))
        lz.put("wp_type", lambda: (
            (sk - 1) % len(WP_TYPES)).astype(jnp.int32))
        lz.put("wp_char_count", lambda: _unif(
            sk, "web_page", "chars", 100, 8_000).astype(jnp.int32))
        lz.put("wp_link_count", lambda: _unif(
            sk, "web_page", "links", 2, 25).astype(jnp.int32))
        lz.put("wp_image_count", lambda: _unif(
            sk, "web_page", "images", 1, 7).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    def _gen_inventory_at(self, idx) -> _Lazy:
        """Weekly (date x item x warehouse) cross product, decoded
        mixed-radix from the row index — the spec's weekly snapshots."""
        wh = idx % self.n_warehouse
        rest = idx // self.n_warehouse
        item = rest % self.n_item
        week = rest // self.n_item
        lz = _Lazy()
        lz.put("inv_date_sk", lambda: jnp.int64(JULIAN_BASE)
               + SALES_START + week * 7)
        lz.put("inv_item_sk", lambda: item + 1)
        lz.put("inv_warehouse_sk", lambda: wh + 1)
        lz.put("inv_quantity_on_hand", lambda: _unif(
            idx, "inventory", "qoh", 0, 1_000).astype(jnp.int32))
        lz.put("__valid__", lambda: jnp.ones_like(idx, dtype=jnp.bool_))
        return lz

    # ------------------------------------------------------ web channel

    def _ws_values(self, slot: jnp.ndarray) -> _Lazy:
        """Per-slot web_sales values; order-structured like the catalog
        channel (order = one customer session, 1..11 live lines)."""
        order = slot // MAX_LINES
        line = slot % MAX_LINES + 1
        key = slot
        m = self._line_money("web_sales", key)
        v = _Lazy()
        v.merge(m)
        v.put("order", lambda: order)
        v.put("line", lambda: line)
        v.put("key", lambda: key)
        v.put("valid", lambda: line <= _unif(
            order, "web_sales", "nlines", 1, MAX_LINES))
        v.put("returned", lambda: v["valid"] & (
            _unif(key, "web_returns", "flag", 0, 99) < WS_RETURN_PCT
        ))
        v.put("customer", lambda: _unif(
            order, "web_sales", "customer", 1, self.n_customer))
        v.put("cdemo", lambda: _unif(
            order, "web_sales", "cdemo", 1, self.n_cdemo))
        v.put("hdemo", lambda: _unif(
            order, "web_sales", "hdemo", 1, self.n_hdemo))
        v.put("addr", lambda: _unif(
            order, "web_sales", "addr", 1, self.n_addr))
        v.put("site", lambda: _unif(
            order, "web_sales", "site", 1, self.n_web_site))
        v.put("page", lambda: _unif(
            order, "web_sales", "page", 1, self.n_web_page))
        v.put("day", lambda: _unif(
            order, "web_sales", "day", SALES_START, SALES_END))
        v.put("tod", lambda: _unif(order, "web_sales", "tod", 0, 86_399))
        v.put("warehouse", lambda: _unif(
            key, "web_sales", "wh", 1, self.n_warehouse))
        v.put("ship_mode", lambda: _unif(
            key, "web_sales", "sm", 1, self.n_ship_mode))
        v.put("item", lambda: _unif(
            key, "web_sales", "item", 1, self.n_item))
        v.put("promo", lambda: _unif(
            key, "web_sales", "promo", 1, self.n_promo))
        return v

    def _gen_web_sales_at(self, idx) -> _Lazy:
        slot = idx
        lz = _Lazy()

        @functools.lru_cache(maxsize=1)
        def wv():
            return self._ws_values(slot)

        lz.put("ws_sold_date_sk",
               lambda: wv()["day"] + jnp.int64(JULIAN_BASE))
        lz.put("ws_sold_time_sk", lambda: wv()["tod"])
        lz.put("ws_ship_date_sk", lambda: (
            # up to 120 days so Q62's 30/60/90+ buckets all populate
            wv()["day"] + _unif(slot, "web_sales", "shiplag", 2, 120)
            + jnp.int64(JULIAN_BASE)))
        lz.put("ws_bill_customer_sk", lambda: wv()["customer"])
        lz.put("ws_bill_cdemo_sk", lambda: wv()["cdemo"])
        lz.put("ws_bill_hdemo_sk", lambda: wv()["hdemo"])
        lz.put("ws_bill_addr_sk", lambda: wv()["addr"])
        lz.put("ws_ship_customer_sk", lambda: wv()["customer"])
        lz.put("ws_ship_addr_sk", lambda: wv()["addr"])
        lz.put("ws_web_site_sk", lambda: wv()["site"])
        lz.put("ws_web_page_sk", lambda: wv()["page"])
        lz.put("ws_warehouse_sk", lambda: wv()["warehouse"])
        lz.put("ws_ship_mode_sk", lambda: wv()["ship_mode"])
        lz.put("ws_item_sk", lambda: wv()["item"])
        lz.put("ws_promo_sk", lambda: wv()["promo"])
        lz.put("ws_order_number", lambda: wv()["order"] + 1)
        lz.put("ws_quantity", lambda: wv()["qty"].astype(jnp.int32))
        lz.put("ws_wholesale_cost", lambda: wv()["whole"])
        lz.put("ws_list_price", lambda: wv()["lst"])
        lz.put("ws_sales_price", lambda: wv()["sprice"])
        lz.put("ws_ext_discount_amt",
               lambda: wv()["qty"] * (wv()["lst"] - wv()["sprice"]))
        lz.put("ws_ext_sales_price", lambda: wv()["ext_sales"])
        lz.put("ws_ext_wholesale_cost",
               lambda: wv()["qty"] * wv()["whole"])
        lz.put("ws_ext_list_price", lambda: wv()["qty"] * wv()["lst"])
        lz.put("ws_ext_tax", lambda: wv()["ext_tax"])
        lz.put("ws_coupon_amt", lambda: jnp.zeros_like(idx, dtype=jnp.int64))
        lz.put("ws_ext_ship_cost", lambda: _unif(
            slot, "web_sales", "shipcost", 0, 5_000))
        lz.put("ws_net_paid", lambda: wv()["net_paid"])
        lz.put("ws_net_paid_inc_tax",
               lambda: wv()["net_paid"] + wv()["ext_tax"])
        lz.put("ws_net_profit", lambda: (
            wv()["net_paid"] - wv()["qty"] * wv()["whole"]))
        lz.put("__valid__", lambda: wv()["valid"])
        return lz

    def _gen_web_returns_at(self, idx) -> _Lazy:
        slot = idx
        lz = _Lazy()

        @functools.lru_cache(maxsize=1)
        def wv():
            return self._ws_values(slot)

        @functools.lru_cache(maxsize=1)
        def rv():
            w = wv()
            return self._return_money("web_returns", w["key"], w)

        lz.put("wr_returned_date_sk",
               lambda: rv()["rday"] + jnp.int64(JULIAN_BASE))
        lz.put("wr_item_sk", lambda: wv()["item"])
        lz.put("wr_refunded_customer_sk", lambda: wv()["customer"])
        lz.put("wr_returning_customer_sk", lambda: wv()["customer"])
        lz.put("wr_web_page_sk", lambda: wv()["page"])
        lz.put("wr_order_number", lambda: wv()["order"] + 1)
        lz.put("wr_reason_sk", lambda: _unif(
            wv()["key"], "web_returns", "reason", 1, self.n_reason))
        lz.put("wr_return_quantity",
               lambda: rv()["rqty"].astype(jnp.int32))
        lz.put("wr_return_amt", lambda: rv()["ramt"])
        lz.put("wr_return_tax", lambda: rv()["rtax"])
        lz.put("wr_return_amt_inc_tax",
               lambda: rv()["ramt"] + rv()["rtax"])
        lz.put("wr_fee", lambda: rv()["fee"])
        lz.put("wr_return_ship_cost", lambda: rv()["ship"])
        lz.put("wr_refunded_cash", lambda: rv()["refunded"])
        lz.put("wr_reversed_charge", lambda: rv()["reversed_c"])
        lz.put("wr_account_credit", lambda: rv()["credit"])
        lz.put("wr_net_loss", lambda: (
            rv()["fee"] + rv()["ship"] + rv()["rtax"]))
        lz.put("__valid__", lambda: wv()["returned"])
        return lz


def _build_schemas() -> Dict[str, TableSchema]:
    V = T.VARCHAR
    B = T.BIGINT
    I = T.INTEGER  # noqa: E741

    def tbl(name, *cols):
        return TableSchema(name, tuple(ColumnSchema(n, t) for n, t in cols))

    return {
        s.name: s
        for s in [
            tbl("date_dim",
                ("d_date_sk", B), ("d_date_id", V), ("d_date", T.DATE),
                ("d_month_seq", I), ("d_week_seq", I), ("d_year", I),
                ("d_dow", I), ("d_moy", I), ("d_dom", I), ("d_qoy", I),
                ("d_quarter_name", V), ("d_day_name", V),
                ("d_weekend", V), ("d_holiday", V), ("d_fy_year", I)),
            tbl("item",
                ("i_item_sk", B), ("i_item_id", V), ("i_item_desc", V),
                ("i_current_price", DEC72), ("i_wholesale_cost", DEC72),
                ("i_brand_id", I), ("i_brand", V), ("i_class_id", I),
                ("i_class", V), ("i_category_id", I), ("i_category", V),
                ("i_manufact_id", I), ("i_manager_id", I), ("i_size", V),
                ("i_color", V), ("i_units", V), ("i_product_name", V)),
            tbl("store",
                ("s_store_sk", B), ("s_store_id", V), ("s_store_name", V),
                ("s_number_employees", I), ("s_floor_space", I),
                ("s_hours", V), ("s_manager", V), ("s_market_id", I),
                ("s_company_id", I), ("s_city", V), ("s_county", V),
                ("s_state", V), ("s_zip", V), ("s_gmt_offset", DEC52),
                ("s_tax_precentage", DEC52)),
            tbl("customer",
                ("c_customer_sk", B), ("c_customer_id", V),
                ("c_current_cdemo_sk", B), ("c_current_hdemo_sk", B),
                ("c_current_addr_sk", B), ("c_first_shipto_date_sk", B),
                ("c_first_sales_date_sk", B), ("c_first_name", V),
                ("c_last_name", V), ("c_birth_day", I),
                ("c_birth_month", I), ("c_birth_year", I)),
            tbl("customer_address",
                ("ca_address_sk", B), ("ca_address_id", V),
                ("ca_street_number", V), ("ca_street_name", V),
                ("ca_street_type", V), ("ca_city", V), ("ca_county", V),
                ("ca_state", V), ("ca_zip", V), ("ca_country", V),
                ("ca_gmt_offset", DEC52), ("ca_location_type", V)),
            tbl("customer_demographics",
                ("cd_demo_sk", B), ("cd_gender", V),
                ("cd_marital_status", V), ("cd_education_status", V),
                ("cd_purchase_estimate", I), ("cd_credit_rating", V),
                ("cd_dep_count", I), ("cd_dep_employed_count", I),
                ("cd_dep_college_count", I)),
            tbl("household_demographics",
                ("hd_demo_sk", B), ("hd_income_band_sk", B),
                ("hd_buy_potential", V), ("hd_dep_count", I),
                ("hd_vehicle_count", I)),
            tbl("income_band",
                ("ib_income_band_sk", B), ("ib_lower_bound", I),
                ("ib_upper_bound", I)),
            tbl("promotion",
                ("p_promo_sk", B), ("p_promo_id", V), ("p_cost", DEC72),
                ("p_response_target", I), ("p_promo_name", V),
                ("p_channel_dmail", V), ("p_channel_email", V),
                ("p_channel_tv", V)),
            tbl("store_sales",
                ("ss_sold_date_sk", B), ("ss_sold_time_sk", B),
                ("ss_item_sk", B), ("ss_customer_sk", B),
                ("ss_cdemo_sk", B), ("ss_hdemo_sk", B), ("ss_addr_sk", B),
                ("ss_store_sk", B), ("ss_promo_sk", B),
                ("ss_ticket_number", B), ("ss_quantity", I),
                ("ss_wholesale_cost", DEC72), ("ss_list_price", DEC72),
                ("ss_sales_price", DEC72),
                ("ss_ext_discount_amt", DEC72),
                ("ss_ext_sales_price", DEC72),
                ("ss_ext_wholesale_cost", DEC72),
                ("ss_ext_list_price", DEC72), ("ss_ext_tax", DEC72),
                ("ss_coupon_amt", DEC72), ("ss_net_paid", DEC72),
                ("ss_net_paid_inc_tax", DEC72), ("ss_net_profit", DEC72)),
            tbl("store_returns",
                ("sr_returned_date_sk", B), ("sr_return_time_sk", B),
                ("sr_item_sk", B), ("sr_customer_sk", B),
                ("sr_cdemo_sk", B), ("sr_hdemo_sk", B), ("sr_addr_sk", B),
                ("sr_store_sk", B), ("sr_reason_sk", B),
                ("sr_ticket_number", B), ("sr_return_quantity", I),
                ("sr_return_amt", DEC72), ("sr_return_tax", DEC72),
                ("sr_return_amt_inc_tax", DEC72), ("sr_fee", DEC72),
                ("sr_return_ship_cost", DEC72),
                ("sr_refunded_cash", DEC72),
                ("sr_reversed_charge", DEC72),
                ("sr_store_credit", DEC72), ("sr_net_loss", DEC72)),
            tbl("catalog_sales",
                ("cs_sold_date_sk", B), ("cs_ship_date_sk", B),
                ("cs_bill_customer_sk", B), ("cs_bill_cdemo_sk", B),
                ("cs_bill_hdemo_sk", B), ("cs_bill_addr_sk", B),
                ("cs_ship_customer_sk", B), ("cs_ship_addr_sk", B),
                ("cs_item_sk", B), ("cs_promo_sk", B),
                ("cs_order_number", B), ("cs_quantity", I),
                ("cs_wholesale_cost", DEC72), ("cs_list_price", DEC72),
                ("cs_sales_price", DEC72),
                ("cs_ext_discount_amt", DEC72),
                ("cs_ext_sales_price", DEC72),
                ("cs_ext_wholesale_cost", DEC72),
                ("cs_ext_list_price", DEC72), ("cs_ext_tax", DEC72),
                ("cs_coupon_amt", DEC72), ("cs_ext_ship_cost", DEC72),
                ("cs_net_paid", DEC72), ("cs_net_paid_inc_tax", DEC72),
                ("cs_net_profit", DEC72)),
            tbl("catalog_returns",
                ("cr_returned_date_sk", B), ("cr_item_sk", B),
                ("cr_refunded_customer_sk", B),
                ("cr_returning_customer_sk", B), ("cr_order_number", B),
                ("cr_return_quantity", I), ("cr_return_amount", DEC72),
                ("cr_return_tax", DEC72),
                ("cr_return_amt_inc_tax", DEC72), ("cr_fee", DEC72),
                ("cr_return_ship_cost", DEC72),
                ("cr_refunded_cash", DEC72),
                ("cr_reversed_charge", DEC72),
                ("cr_store_credit", DEC72), ("cr_net_loss", DEC72),
                ("cr_reason_sk", B)),
            # ---- round 3: web channel + remaining dims (24 tables)
            tbl("warehouse",
                ("w_warehouse_sk", B), ("w_warehouse_id", V),
                ("w_warehouse_name", V), ("w_warehouse_sq_ft", I),
                ("w_city", V), ("w_county", V), ("w_state", V),
                ("w_zip", V), ("w_country", V),
                ("w_gmt_offset", DEC52)),
            tbl("ship_mode",
                ("sm_ship_mode_sk", B), ("sm_ship_mode_id", V),
                ("sm_type", V), ("sm_code", V), ("sm_carrier", V),
                ("sm_contract", V)),
            tbl("reason",
                ("r_reason_sk", B), ("r_reason_id", V),
                ("r_reason_desc", V)),
            tbl("time_dim",
                ("t_time_sk", B), ("t_time_id", V), ("t_time", I),
                ("t_hour", I), ("t_minute", I), ("t_second", I),
                ("t_am_pm", V), ("t_shift", V), ("t_sub_shift", V),
                ("t_meal_time", V)),
            tbl("call_center",
                ("cc_call_center_sk", B), ("cc_call_center_id", V),
                ("cc_name", V), ("cc_class", V), ("cc_employees", I),
                ("cc_sq_ft", I), ("cc_hours", V), ("cc_manager", V),
                ("cc_market_id", I), ("cc_county", V), ("cc_state", V)),
            tbl("catalog_page",
                ("cp_catalog_page_sk", B), ("cp_catalog_page_id", V),
                ("cp_start_date_sk", B), ("cp_end_date_sk", B),
                ("cp_department", V), ("cp_catalog_number", I),
                ("cp_catalog_page_number", I), ("cp_description", V),
                ("cp_type", V)),
            tbl("web_site",
                ("web_site_sk", B), ("web_site_id", V), ("web_name", V),
                ("web_open_date_sk", B), ("web_manager", V),
                ("web_market_id", I), ("web_company_id", I),
                ("web_company_name", V), ("web_gmt_offset", DEC52),
                ("web_tax_percentage", DEC52)),
            tbl("web_page",
                ("wp_web_page_sk", B), ("wp_web_page_id", V),
                ("wp_creation_date_sk", B), ("wp_access_date_sk", B),
                ("wp_autogen_flag", V), ("wp_customer_sk", B),
                ("wp_url", V), ("wp_type", V), ("wp_char_count", I),
                ("wp_link_count", I), ("wp_image_count", I)),
            tbl("inventory",
                ("inv_date_sk", B), ("inv_item_sk", B),
                ("inv_warehouse_sk", B), ("inv_quantity_on_hand", I)),
            tbl("web_sales",
                ("ws_sold_date_sk", B), ("ws_sold_time_sk", B),
                ("ws_ship_date_sk", B), ("ws_bill_customer_sk", B),
                ("ws_bill_cdemo_sk", B), ("ws_bill_hdemo_sk", B),
                ("ws_bill_addr_sk", B), ("ws_ship_customer_sk", B),
                ("ws_ship_addr_sk", B), ("ws_web_site_sk", B),
                ("ws_web_page_sk", B), ("ws_warehouse_sk", B),
                ("ws_ship_mode_sk", B), ("ws_item_sk", B),
                ("ws_promo_sk", B), ("ws_order_number", B),
                ("ws_quantity", I), ("ws_wholesale_cost", DEC72),
                ("ws_list_price", DEC72), ("ws_sales_price", DEC72),
                ("ws_ext_discount_amt", DEC72),
                ("ws_ext_sales_price", DEC72),
                ("ws_ext_wholesale_cost", DEC72),
                ("ws_ext_list_price", DEC72), ("ws_ext_tax", DEC72),
                ("ws_coupon_amt", DEC72), ("ws_ext_ship_cost", DEC72),
                ("ws_net_paid", DEC72), ("ws_net_paid_inc_tax", DEC72),
                ("ws_net_profit", DEC72)),
            tbl("web_returns",
                ("wr_returned_date_sk", B), ("wr_item_sk", B),
                ("wr_refunded_customer_sk", B),
                ("wr_returning_customer_sk", B), ("wr_web_page_sk", B),
                ("wr_order_number", B), ("wr_reason_sk", B),
                ("wr_return_quantity", I), ("wr_return_amt", DEC72),
                ("wr_return_tax", DEC72),
                ("wr_return_amt_inc_tax", DEC72), ("wr_fee", DEC72),
                ("wr_return_ship_cost", DEC72),
                ("wr_refunded_cash", DEC72),
                ("wr_reversed_charge", DEC72),
                ("wr_account_credit", DEC72), ("wr_net_loss", DEC72)),
        ]
    }
