"""SQL frontend: tokenizer, recursive-descent parser, analyzer/planner.

Reference: presto-parser (ANTLR SqlBase.g4 grammar -> sql/tree/* AST,
~150 node classes) and presto-main sql/analyzer + sql/planner. Per SURVEY
§8.1.4 we do NOT port the grammar wholesale: this is a hand-written
recursive-descent/Pratt parser over the SQL-92+ subset that TPC-H/TPC-DS
exercise, feeding a planner that lowers straight to typed physical plans
with predicate pushdown, column pruning, join-key extraction, and subquery
decorrelation folded into the lowering (the reference spreads these across
PlanOptimizers passes; ours are integrated because the plan space is
narrower).
"""

from presto_tpu.sql.parser import parse  # noqa: F401
