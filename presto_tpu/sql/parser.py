"""Hand-written SQL tokenizer + recursive-descent parser.

Reference: presto-parser's ANTLR grammar
(presto-parser/src/main/antlr4/.../SqlBase.g4) and AstBuilder. Deliberately
NOT a grammar port (SURVEY §8.1.4): a compact Pratt parser covering the
SQL-92+ subset TPC-H/TPC-DS use — SELECT blocks with joins, subqueries
(FROM/scalar/IN/EXISTS), WITH, set operations, CASE, CAST, EXTRACT,
LIKE/BETWEEN/IN, date/interval literals, EXPLAIN.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

from presto_tpu.sql import ast_nodes as N

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*|/\*.*?\*/)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?
      |\d+[eE][+-]?\d+|\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<dquoted>"(?:[^"]|"")*")
  | (?P<name>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|->|\|\||[=<>+\-*/%(),.;?\[\]])
    """,
    re.VERBOSE | re.DOTALL,
)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "exists", "between", "like",
    "escape", "is", "null", "case", "when", "then", "else", "end", "cast",
    "extract", "distinct", "all", "union", "intersect", "except", "join",
    "inner", "left", "right", "full", "outer", "cross", "on", "using",
    "with", "asc", "desc", "nulls", "first", "last", "date", "time",
    "timestamp", "interval", "true", "false", "explain", "analyze",
    "substring", "for", "create", "table", "drop", "insert", "into",
    "set", "session", "show", "tables", "over", "partition",
    "delete", "update", "grouping", "sets", "rollup", "cube",
    "unnest", "ordinality", "array",
    "rows", "range", "unbounded", "preceding", "following", "current",
    "row", "view", "prepare", "execute", "deallocate",
}


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind, value, pos):
        self.kind = kind  # number | string | name | keyword | op | eof
        self.value = value
        self.pos = pos

    def __repr__(self):  # pragma: no cover
        return f"Token({self.kind}, {self.value!r})"


def tokenize(text: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise SqlSyntaxError(
                f"unexpected character {text[pos]!r} at {pos}"
            )
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        val = m.group()
        if m.lastgroup == "name":
            low = val.lower()
            out.append(
                Token("keyword" if low in KEYWORDS else "name", low, m.start())
            )
        elif m.lastgroup == "string":
            out.append(
                Token("string", val[1:-1].replace("''", "'"), m.start())
            )
        elif m.lastgroup == "dquoted":
            out.append(
                Token("name", val[1:-1].replace('""', '"'), m.start())
            )
        else:
            out.append(Token(m.lastgroup, val, m.start()))
    out.append(Token("eof", None, pos))
    return out


class SqlSyntaxError(ValueError):
    pass


# Pratt binding powers for binary operators
_PRECEDENCE = {
    "or": 1,
    "and": 2,
    # NOT handled as prefix at level 3
    "=": 4, "<>": 4, "!=": 4, "<": 4, "<=": 4, ">": 4, ">=": 4,
    # BETWEEN/IN/LIKE/IS handled at level 4 specially
    "||": 5,
    "+": 6, "-": 6,
    "*": 7, "/": 7, "%": 7,
}


class Parser:
    def __init__(self, tokens: List[Token], source: Optional[str] = None):
        self.toks = tokens
        self.i = 0
        self.source = source  # raw SQL (DML expression slicing)

    # ------------------------------------------------------------ cursor
    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_keyword(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "keyword" and t.value in kws

    def accept_keyword(self, *kws: str) -> bool:
        if self.at_keyword(*kws):
            self.next()
            return True
        return False

    def expect_keyword(self, kw: str):
        if not self.accept_keyword(kw):
            raise SqlSyntaxError(
                f"expected {kw.upper()} at position {self.peek().pos}, "
                f"found {self.peek().value!r}"
            )

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.value == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.accept_op(op):
            raise SqlSyntaxError(
                f"expected {op!r} at position {self.peek().pos}, found "
                f"{self.peek().value!r}"
            )

    def expect_name(self) -> str:
        t = self.next()
        if t.kind not in ("name", "keyword"):
            raise SqlSyntaxError(f"expected identifier, found {t.value!r}")
        return t.value

    # ----------------------------------------------------------- toplevel
    def parse_statement(self) -> N.Node:
        if self.accept_keyword("explain"):
            analyze = self.accept_keyword("analyze")
            q = self.parse_query()
            self._finish()
            return N.Explain(q, analyze)
        if self.accept_keyword("create"):
            replace = False
            if self.accept_keyword("or"):
                if self.expect_name() != "replace":
                    raise SqlSyntaxError("expected REPLACE after OR")
                replace = True
            if self.accept_keyword("view"):
                parts = self._qualified_name()
                self.expect_keyword("as")
                start = self.peek().pos
                self.parse_query()  # validate the definition parses
                self._finish()
                return N.CreateView(
                    parts, self.source[start:].strip().rstrip(";"),
                    replace,
                )
            self.expect_keyword("table")
            if replace:
                raise SqlSyntaxError(
                    "CREATE OR REPLACE is supported for views only"
                )
            parts = self._qualified_name()
            self.expect_keyword("as")
            q = self.parse_query()
            self._finish()
            return N.CreateTableAs(parts, q)
        if self.accept_keyword("insert"):
            self.expect_keyword("into")
            parts = self._qualified_name()
            q = self.parse_query()
            self._finish()
            return N.InsertInto(parts, q)
        if self.accept_keyword("drop"):
            if self.accept_keyword("view"):
                parts = self._qualified_name()
                self._finish()
                return N.DropView(parts)
            self.expect_keyword("table")
            parts = self._qualified_name()
            self._finish()
            return N.DropTable(parts)
        if self.accept_keyword("prepare"):
            name = self.expect_name()
            self.expect_keyword("from")
            start = self.peek().pos
            text = self.source[start:].strip().rstrip(";")
            # validate the inner statement parses (parameters allowed)
            Parser(tokenize(text), source=text).parse_statement()
            while self.peek().kind != "eof":
                self.next()
            return N.Prepare(name, text)
        if self.accept_keyword("execute"):
            name = self.expect_name()
            args: List[N.Node] = []
            arg_sqls: List[str] = []
            if self.accept_keyword("using"):
                while True:
                    start = self.peek().pos
                    args.append(self.parse_expr())
                    end = self.peek().pos
                    arg_sqls.append(
                        self.source[start:end].strip().rstrip(";"))
                    if not self.accept_op(","):
                        break
            self._finish()
            return N.ExecutePrepared(name, tuple(args), tuple(arg_sqls))
        if self.accept_keyword("deallocate"):
            self.accept_keyword("prepare")
            name = self.expect_name()
            self._finish()
            return N.Deallocate(name)
        if self.accept_keyword("delete"):
            # DML rewrites re-plan through SELECT (runner), so the
            # predicate/assignment expressions ride as raw SQL slices
            self.expect_keyword("from")
            parts = self._qualified_name()
            where_sql = None
            if self.accept_keyword("where"):
                where_sql = self._expr_text()
            self._finish()
            return N.Delete(parts, where_sql)
        if self.accept_keyword("update"):
            parts = self._qualified_name()
            self.expect_keyword("set")
            assignments = []
            while True:
                col = self.expect_name()
                self.expect_op("=")
                assignments.append((col, self._expr_text()))
                if not self.accept_op(","):
                    break
            where_sql = None
            if self.accept_keyword("where"):
                where_sql = self._expr_text()
            self._finish()
            return N.Update(parts, tuple(assignments), where_sql)
        if self.accept_keyword("set"):
            self.expect_keyword("session")
            name = self.expect_name()
            self.expect_op("=")
            t = self.next()
            if t.kind in ("string", "number"):
                value = t.value
            elif t.kind == "keyword" and t.value in ("true", "false"):
                value = t.value
            elif t.kind == "name":
                value = t.value
            else:
                raise SqlSyntaxError(
                    f"expected session value, found {t.value!r}"
                )
            self._finish()
            return N.SetSession(name, value)
        if self.accept_keyword("show"):
            if self.accept_keyword("session"):
                self._finish()
                return N.ShowSession()
            if self.accept_keyword("tables"):
                catalog = None
                if self.accept_keyword("from") or self.accept_keyword("in"):
                    catalog = self.expect_name()
                self._finish()
                return N.ShowTables(catalog)
            raise SqlSyntaxError("expected SESSION or TABLES after SHOW")
        q = self.parse_query()
        self._finish()
        return q

    def _expr_text(self) -> str:
        """Parse an expression, returning its raw source slice (needs
        the source attached by parse()); used by DML statements whose
        expressions are re-planned inside generated SELECTs."""
        start = self.peek().pos
        self.parse_expr()
        end = self.peek().pos
        if self.source is None:  # pragma: no cover - direct Parser use
            raise SqlSyntaxError("DML parsing requires source text")
        return self.source[start:end].strip()

    def _qualified_name(self) -> Tuple[str, ...]:
        parts = [self.expect_name()]
        while self.accept_op("."):
            parts.append(self.expect_name())
        return tuple(parts)

    def _finish(self):
        self.accept_op(";")
        if self.peek().kind != "eof":
            raise SqlSyntaxError(
                f"trailing input at {self.peek().pos}: {self.peek().value!r}"
            )

    def parse_query(self) -> N.Query:
        withs: List[N.With] = []
        if self.accept_keyword("with"):
            while True:
                name = self.expect_name()
                col_aliases: Tuple[str, ...] = ()
                if self.accept_op("("):
                    cols = [self.expect_name()]
                    while self.accept_op(","):
                        cols.append(self.expect_name())
                    self.expect_op(")")
                    col_aliases = tuple(cols)
                self.expect_keyword("as")
                self.expect_op("(")
                sub = self.parse_query()
                self.expect_op(")")
                withs.append(N.With(name, col_aliases, sub))
                if not self.accept_op(","):
                    break
        body = self.parse_set_expr()
        # ORDER BY / LIMIT / OFFSET bind to the whole body (incl. across
        # UNION branches) — never to an individual set-op operand
        order_by: Tuple[N.OrderItem, ...] = ()
        limit = None
        offset = 0
        if self.at_keyword("order"):
            order_by = self.parse_order_by()
        if self.accept_keyword("limit"):
            limit = int(self.next().value)
        if self.accept_keyword("offset"):
            offset = int(self.next().value)
        return N.Query(body=body, withs=tuple(withs), order_by=order_by,
                       limit=limit, offset=offset)

    def parse_set_expr(self) -> N.Node:
        left = self.parse_query_term()
        while self.at_keyword("union", "intersect", "except"):
            op = self.next().value
            if op == "union":
                op = "union_all" if self.accept_keyword("all") else "union"
                self.accept_keyword("distinct")
            else:
                self.accept_keyword("all", "distinct")
            right = self.parse_query_term()
            left = N.SetOp(op, left, right)
        return left

    def parse_query_term(self) -> N.Node:
        if self.accept_op("("):
            q = self.parse_query()
            self.expect_op(")")
            return q
        return self.parse_query_spec()

    def parse_query_spec(self) -> N.QuerySpec:
        self.expect_keyword("select")
        distinct = False
        if self.accept_keyword("distinct"):
            distinct = True
        else:
            self.accept_keyword("all")
        select = [self.parse_select_item()]
        while self.accept_op(","):
            select.append(self.parse_select_item())

        from_: List[N.Node] = []
        if self.accept_keyword("from"):
            from_.append(self.parse_relation())
            while self.accept_op(","):
                from_.append(self.parse_relation())

        where = self.parse_expr() if self.accept_keyword("where") else None

        group_by: List[N.Node] = []
        grouping_sets = None
        if self.accept_keyword("group"):
            self.expect_keyword("by")
            group_by, grouping_sets = self.parse_group_by_body()

        having = self.parse_expr() if self.accept_keyword("having") else None

        return N.QuerySpec(
            select=tuple(select), distinct=distinct, from_=tuple(from_),
            where=where, group_by=tuple(group_by), having=having,
            order_by=(), limit=None, offset=0,
            grouping_sets=grouping_sets,
        )

    def parse_group_by_body(self):
        """Plain key list, or GROUPING SETS / ROLLUP / CUBE (reference:
        SqlBase.g4 groupingElement). Returns (union key list, set index
        tuples or None)."""

        def key_index(keys: List[N.Node], e: N.Node) -> int:
            for i, k in enumerate(keys):
                if k == e:
                    return i
            keys.append(e)
            return len(keys) - 1

        if self.accept_keyword("grouping"):
            self.expect_keyword("sets")
            self.expect_op("(")
            keys: List[N.Node] = []
            sets: List[Tuple[int, ...]] = []
            while True:
                self.expect_op("(")
                members: List[int] = []
                if not self.accept_op(")"):
                    members.append(key_index(keys, self.parse_expr()))
                    while self.accept_op(","):
                        members.append(key_index(keys, self.parse_expr()))
                    self.expect_op(")")
                sets.append(tuple(members))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return keys, tuple(sets)
        if self.accept_keyword("rollup"):
            self.expect_op("(")
            keys = [self.parse_expr()]
            while self.accept_op(","):
                keys.append(self.parse_expr())
            self.expect_op(")")
            n = len(keys)
            return keys, tuple(
                tuple(range(k)) for k in range(n, -1, -1)
            )
        if self.accept_keyword("cube"):
            self.expect_op("(")
            keys = [self.parse_expr()]
            while self.accept_op(","):
                keys.append(self.parse_expr())
            self.expect_op(")")
            n = len(keys)
            return keys, tuple(
                tuple(i for i in range(n) if mask & (1 << i))
                for mask in range((1 << n) - 1, -1, -1)
            )
        group_by = [self.parse_expr()]
        while self.accept_op(","):
            group_by.append(self.parse_expr())
        return group_by, None

    def parse_order_by(self) -> Tuple[N.OrderItem, ...]:
        self.expect_keyword("order")
        self.expect_keyword("by")
        items = [self.parse_order_item()]
        while self.accept_op(","):
            items.append(self.parse_order_item())
        return tuple(items)

    def parse_order_item(self) -> N.OrderItem:
        e = self.parse_expr()
        asc = True
        if self.accept_keyword("desc"):
            asc = False
        else:
            self.accept_keyword("asc")
        nulls_first = None
        if self.accept_keyword("nulls"):
            if self.accept_keyword("first"):
                nulls_first = True
            else:
                self.expect_keyword("last")
                nulls_first = False
        return N.OrderItem(e, asc, nulls_first)

    def parse_select_item(self) -> N.SelectItem:
        if self.accept_op("*"):
            return N.SelectItem(N.Star())
        # qualified star: t.*
        if (
            self.peek().kind == "name"
            and self.peek(1).kind == "op" and self.peek(1).value == "."
            and self.peek(2).kind == "op" and self.peek(2).value == "*"
        ):
            q = self.next().value
            self.next()
            self.next()
            return N.SelectItem(N.Star(qualifier=q))
        e = self.parse_expr()
        alias = None
        if self.accept_keyword("as"):
            alias = self.expect_name()
        elif self.peek().kind == "name":
            alias = self.next().value
        return N.SelectItem(e, alias)

    # ---------------------------------------------------------- relations
    def parse_relation(self) -> N.Node:
        left = self.parse_aliased_relation()
        while True:
            if self.accept_keyword("cross"):
                self.expect_keyword("join")
                right = self.parse_aliased_relation()
                left = N.JoinRelation("cross", left, right)
                continue
            jt = None
            if self.accept_keyword("inner"):
                jt = "inner"
                self.expect_keyword("join")
            elif self.at_keyword("left", "right", "full"):
                jt = self.next().value
                self.accept_keyword("outer")
                self.expect_keyword("join")
            elif self.accept_keyword("join"):
                jt = "inner"
            if jt is None:
                return left
            right = self.parse_aliased_relation()
            on = None
            if self.accept_keyword("on"):
                on = self.parse_expr()
            elif self.accept_keyword("using"):
                self.expect_op("(")
                cols = [self.expect_name()]
                while self.accept_op(","):
                    cols.append(self.expect_name())
                self.expect_op(")")
                on = ("using", tuple(cols))
            left = N.JoinRelation(jt, left, right, on)

    def parse_aliased_relation(self) -> N.Node:
        if self.accept_keyword("unnest"):
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_op(")")
            with_ord = False
            if self.accept_keyword("with"):
                self.expect_keyword("ordinality")
                with_ord = True
            rel = N.UnnestRelation(e, with_ord)
        elif self.accept_op("("):
            if self.at_keyword("select", "with"):
                rel: N.Node = N.SubqueryRelation(self.parse_query())
            else:
                rel = self.parse_relation()
            self.expect_op(")")
        else:
            parts = [self.expect_name()]
            while self.accept_op("."):
                parts.append(self.expect_name())
            rel = N.Table(tuple(parts))
        alias = None
        cols: Tuple[str, ...] = ()
        if self.accept_keyword("as"):
            alias = self.expect_name()
        elif self.peek().kind == "name":
            alias = self.next().value
        if alias and self.accept_op("("):
            cs = [self.expect_name()]
            while self.accept_op(","):
                cs.append(self.expect_name())
            self.expect_op(")")
            cols = tuple(cs)
        if alias:
            return N.AliasedRelation(rel, alias, cols)
        return rel

    # -------------------------------------------------------- expressions
    def parse_expr(self, min_bp: int = 0) -> N.Node:
        left = self.parse_prefix()
        while True:
            t = self.peek()
            # NOT BETWEEN / NOT IN / NOT LIKE
            if t.kind == "keyword" and t.value == "not" and self.peek(
                    1).kind == "keyword" and self.peek(1).value in (
                    "between", "in", "like"):
                if 4 < min_bp:
                    return left
                self.next()
                left = self.parse_postfix_predicate(left, negated=True)
                continue
            if t.kind == "keyword" and t.value in ("between", "in", "like"):
                if 4 < min_bp:
                    return left
                left = self.parse_postfix_predicate(left, negated=False)
                continue
            if t.kind == "keyword" and t.value == "is":
                if 4 < min_bp:
                    return left
                self.next()
                negated = self.accept_keyword("not")
                self.expect_keyword("null")
                left = N.IsNull(left, negated)
                continue
            op = None
            if t.kind == "op" and t.value in _PRECEDENCE:
                op = t.value
            elif t.kind == "keyword" and t.value in ("and", "or"):
                op = t.value
            if op is None:
                return left
            bp = _PRECEDENCE[op]
            if bp < min_bp:
                return left
            self.next()
            right = self.parse_expr(bp + 1)
            if op == "!=":
                op = "<>"
            left = N.BinaryOp(op, left, right)

    def parse_postfix_predicate(self, left: N.Node, negated: bool) -> N.Node:
        if self.accept_keyword("between"):
            low = self.parse_expr(5)
            self.expect_keyword("and")
            high = self.parse_expr(5)
            return N.Between(left, low, high, negated)
        if self.accept_keyword("in"):
            self.expect_op("(")
            if self.at_keyword("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                return N.InSubquery(left, q, negated)
            items = [self.parse_expr()]
            while self.accept_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return N.InList(left, tuple(items), negated)
        if self.accept_keyword("like"):
            pattern = self.parse_expr(5)
            escape = None
            if self.accept_keyword("escape"):
                escape = self.parse_expr(5)
            return N.Like(left, pattern, escape, negated)
        raise SqlSyntaxError("bad postfix predicate")  # pragma: no cover

    def parse_prefix(self) -> N.Node:
        t = self.peek()
        if t.kind == "op" and t.value == "(":
            self.next()
            if self.at_keyword("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                return N.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "op" and t.value in ("-", "+"):
            self.next()
            return N.UnaryOp(t.value, self.parse_expr(8))
        if t.kind == "op" and t.value == "?":
            self.next()
            idx = getattr(self, "_param_count", 0)
            self._param_count = idx + 1
            return N.Parameter(idx)
        if t.kind == "keyword":
            return self.parse_keyword_expr()
        if t.kind == "number":
            self.next()
            v = t.value
            if "." in v or "e" in v.lower():
                # exact decimal literal unless exponent present (reference:
                # parser DecimalLiteral vs DoubleLiteral)
                if "e" in v.lower():
                    return N.Literal("double", float(v))
                return N.Literal("decimal", v)
            return N.Literal("long", int(v))
        if t.kind == "string":
            self.next()
            return N.Literal("string", t.value)
        if t.kind == "name":
            return self.parse_name_expr()
        raise SqlSyntaxError(f"unexpected token {t.value!r} at {t.pos}")

    def parse_keyword_expr(self) -> N.Node:
        if self.accept_keyword("not"):
            return N.UnaryOp("not", self.parse_expr(3))
        if self.accept_keyword("array"):
            self.expect_op("[")
            items: List[N.Node] = []
            if not self.accept_op("]"):
                items.append(self.parse_expr())
                while self.accept_op(","):
                    items.append(self.parse_expr())
                self.expect_op("]")
            return N.ArrayLiteral(tuple(items))
        if self.accept_keyword("exists"):
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return N.Exists(q)
        if self.accept_keyword("true"):
            return N.Literal("boolean", True)
        if self.accept_keyword("false"):
            return N.Literal("boolean", False)
        if self.accept_keyword("null"):
            return N.Literal("null", None)
        if self.accept_keyword("date"):
            lit = self.next()
            if lit.kind != "string":
                raise SqlSyntaxError("DATE literal needs a string")
            return N.Literal("date", lit.value)
        if self.accept_keyword("timestamp"):
            lit = self.next()
            return N.Literal("timestamp", lit.value)
        if self.accept_keyword("interval"):
            sign = -1 if self.accept_op("-") else 1
            lit = self.next()
            if lit.kind != "string":
                raise SqlSyntaxError("INTERVAL literal needs a string")
            unit = self.expect_name()
            return N.Literal("interval", (sign * int(lit.value), unit))
        if self.accept_keyword("case"):
            operand = None
            if not self.at_keyword("when"):
                operand = self.parse_expr()
            whens = []
            while self.accept_keyword("when"):
                cond = self.parse_expr()
                self.expect_keyword("then")
                whens.append((cond, self.parse_expr()))
            default = None
            if self.accept_keyword("else"):
                default = self.parse_expr()
            self.expect_keyword("end")
            return N.Case(operand, tuple(whens), default)
        if self.accept_keyword("cast"):
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_keyword("as")
            type_name = self._parse_type_name()
            self.expect_op(")")
            return N.Cast(e, type_name)
        if self.accept_keyword("extract"):
            self.expect_op("(")
            field = self.expect_name()
            self.expect_keyword("from")
            e = self.parse_expr()
            self.expect_op(")")
            return N.Extract(field, e)
        if self.accept_keyword("substring"):
            # substring(x from a [for b]) and substring(x, a [, b])
            self.expect_op("(")
            e = self.parse_expr()
            args = [e]
            if self.accept_keyword("from"):
                args.append(self.parse_expr())
                if self.accept_keyword("for"):
                    args.append(self.parse_expr())
            else:
                while self.accept_op(","):
                    args.append(self.parse_expr())
            self.expect_op(")")
            return N.FunctionCall("substr", tuple(args))
        # keywords usable as function names / identifiers (left, right...)
        return self.parse_name_expr()

    def _parse_type_name(self) -> str:
        name = self.expect_name()
        if self.accept_op("("):
            args = [self.next().value]
            while self.accept_op(","):
                args.append(self.next().value)
            self.expect_op(")")
            return f"{name}({','.join(str(a) for a in args)})"
        # two-word types
        if name == "double" and self.accept_keyword("precision"):
            return "double"
        return name

    def _parse_call_arg(self) -> N.Node:
        """One function-call argument; detects lambda syntax
        `x -> body` / `(x, y) -> body` (reference:
        sql/tree/LambdaExpression for higher-order functions)."""
        t = self.peek()
        if (t.kind == "name" and self.peek(1).kind == "op"
                and self.peek(1).value == "->"):
            p = self.next().value
            self.next()  # ->
            return N.Lambda((p,), self.parse_expr())
        if t.kind == "op" and t.value == "(":
            j, params = 1, []
            is_lambda = False
            while True:
                tk = self.peek(j)
                if tk.kind != "name":
                    break
                params.append(tk.value)
                nxt = self.peek(j + 1)
                if nxt.kind == "op" and nxt.value == ",":
                    j += 2
                    continue
                if nxt.kind == "op" and nxt.value == ")":
                    after = self.peek(j + 2)
                    is_lambda = after.kind == "op" and after.value == "->"
                break
            if is_lambda and params:
                for _ in range(2 * len(params) + 2):
                    self.next()  # ( p1 , ... pN ) ->
                return N.Lambda(tuple(params), self.parse_expr())
        return self.parse_expr()

    def parse_name_expr(self) -> N.Node:
        t = self.next()
        if t.kind not in ("name", "keyword"):
            raise SqlSyntaxError(f"unexpected token {t.value!r} at {t.pos}")
        name = t.value
        # TRY_CAST(e AS type) shares CAST's special syntax
        if (name == "try_cast" and self.peek().kind == "op"
                and self.peek().value == "("):
            self.next()
            e = self.parse_expr()
            self.expect_keyword("as")
            type_name = self._parse_type_name()
            self.expect_op(")")
            return N.Cast(e, type_name, safe=True)
        # function call?
        if self.peek().kind == "op" and self.peek().value == "(":
            self.next()
            if self.accept_op("*"):
                self.expect_op(")")
                return self._maybe_over(
                    N.FunctionCall(name, (), is_star=True)
                )
            distinct = False
            args: List[N.Node] = []
            if not (self.peek().kind == "op" and self.peek().value == ")"):
                if self.accept_keyword("distinct"):
                    distinct = True
                else:
                    self.accept_keyword("all")
                args.append(self._parse_call_arg())
                while self.accept_op(","):
                    args.append(self._parse_call_arg())
            self.expect_op(")")
            return self._maybe_over(
                N.FunctionCall(name, tuple(args), distinct=distinct)
            )
        parts = [name]
        while self.peek().kind == "op" and self.peek().value == ".":
            self.next()
            parts.append(self.expect_name())
        return N.Identifier(tuple(parts))

    def _maybe_over(self, call: N.FunctionCall) -> N.Node:
        """fn(...) [OVER ( [PARTITION BY e,...] [ORDER BY ...]
        [ROWS|RANGE frame] )] (reference: sql/tree/WindowFrame)"""
        if not self.accept_keyword("over"):
            return call
        self.expect_op("(")
        partition: List[N.Node] = []
        order: Tuple[N.OrderItem, ...] = ()
        if self.accept_keyword("partition"):
            self.expect_keyword("by")
            partition.append(self.parse_expr())
            while self.accept_op(","):
                partition.append(self.parse_expr())
        if self.at_keyword("order"):
            order = self.parse_order_by()
        frame = None
        if self.at_keyword("rows", "range"):
            unit = self.next().value
            if self.accept_keyword("between"):
                start = self._frame_bound()
                self.expect_keyword("and")
                end = self._frame_bound()
            else:
                start = self._frame_bound()
                end = ("current", None)
            frame = (unit, start, end)
        self.expect_op(")")
        import dataclasses as _dc

        return _dc.replace(
            call,
            window=N.WindowSpec(tuple(partition), tuple(order), frame),
        )

    def _frame_bound(self):
        """UNBOUNDED PRECEDING|FOLLOWING, CURRENT ROW, or
        <n> PRECEDING|FOLLOWING."""
        if self.accept_keyword("unbounded"):
            if self.accept_keyword("preceding"):
                return ("unbounded_preceding", None)
            self.expect_keyword("following")
            return ("unbounded_following", None)
        if self.accept_keyword("current"):
            self.expect_keyword("row")
            return ("current", None)
        t = self.next()
        if t.kind != "number" or "." in str(t.value):
            raise SqlSyntaxError(
                f"frame bound must be an integer, got {t.value!r}"
            )
        n = int(t.value)
        if self.accept_keyword("preceding"):
            return ("preceding", n)
        self.expect_keyword("following")
        return ("following", n)


def parse(sql: str) -> N.Node:
    """Parse one statement (reference: SqlParser.createStatement)."""
    return Parser(tokenize(sql), source=sql).parse_statement()
