"""SQL AST.

Reference: presto-parser sql/tree/* (Query, QuerySpecification, Select,
Join, comparison/arithmetic expression nodes, ...). Dataclasses, one per
syntactic form; the planner consumes these directly. Names mirror the
reference's where a node corresponds 1:1.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


class Node:
    pass


# ------------------------------------------------------------- expressions

@dataclasses.dataclass(frozen=True)
class Identifier(Node):
    """Column reference, possibly qualified (t.col)."""

    parts: Tuple[str, ...]

    @property
    def name(self) -> str:
        return self.parts[-1]

    @property
    def qualifier(self) -> Optional[str]:
        return self.parts[-2] if len(self.parts) > 1 else None


@dataclasses.dataclass(frozen=True)
class Literal(Node):
    """kind: 'long' | 'double' | 'decimal' | 'string' | 'boolean' | 'null'
    | 'date' | 'interval'. value for interval: (amount, unit)."""

    kind: str
    value: object


@dataclasses.dataclass(frozen=True)
class Star(Node):
    qualifier: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class UnaryOp(Node):
    op: str  # '-' | '+' | 'not'
    operand: Node


@dataclasses.dataclass(frozen=True)
class BinaryOp(Node):
    op: str  # + - * / % = <> < <= > >= and or
    left: Node
    right: Node


@dataclasses.dataclass(frozen=True)
class Between(Node):
    value: Node
    low: Node
    high: Node
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InList(Node):
    value: Node
    items: Tuple[Node, ...]
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InSubquery(Node):
    value: Node
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Exists(Node):
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ScalarSubquery(Node):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Like(Node):
    value: Node
    pattern: Node
    escape: Optional[Node] = None
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class IsNull(Node):
    value: Node
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Case(Node):
    """Searched or simple CASE (operand not None => simple)."""

    operand: Optional[Node]
    whens: Tuple[Tuple[Node, Node], ...]
    default: Optional[Node]


@dataclasses.dataclass(frozen=True)
class Cast(Node):
    value: Node
    type_name: str
    # TRY_CAST(x AS t): parse failures yield NULL (reference:
    # TryCastFunction). Plain CAST also NULLs unparsable varchar under
    # the masked-eval policy — `safe` keeps the surface distinction.
    safe: bool = False


@dataclasses.dataclass(frozen=True)
class FunctionCall(Node):
    name: str
    args: Tuple[Node, ...]
    distinct: bool = False
    is_star: bool = False  # count(*)
    window: Optional["WindowSpec"] = None  # fn(...) OVER (...)


@dataclasses.dataclass(frozen=True)
class Lambda(Node):
    """x -> expr / (x, y) -> expr argument to a higher-order function
    (reference: sql/tree/LambdaExpression)."""

    params: Tuple[str, ...]
    body: Node


@dataclasses.dataclass(frozen=True)
class WindowSpec(Node):
    """OVER clause (reference: sql/tree/Window + WindowFrame). `frame`
    is None for the SQL default (RANGE UNBOUNDED PRECEDING..CURRENT ROW
    with an ORDER BY, the whole partition without), else
    (unit, start_bound, end_bound) with unit in {"rows", "range"} and
    each bound ("unbounded_preceding"|"preceding"|"current"|
    "following"|"unbounded_following", n_or_None)."""

    partition_by: Tuple[Node, ...] = ()
    order_by: Tuple["OrderItem", ...] = ()
    frame: Optional[Tuple] = None


@dataclasses.dataclass(frozen=True)
class Extract(Node):
    field: str  # year | month | day | ...
    value: Node


# --------------------------------------------------------------- relations

@dataclasses.dataclass(frozen=True)
class Table(Node):
    parts: Tuple[str, ...]  # [catalog.][schema.]table


@dataclasses.dataclass(frozen=True)
class AliasedRelation(Node):
    relation: Node
    alias: str
    column_aliases: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class SubqueryRelation(Node):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class UnnestRelation(Node):
    """UNNEST(expr) [WITH ORDINALITY] — lateral expansion of an array
    expression over the preceding FROM items (reference:
    sql/tree/Unnest)."""

    expr: Node
    with_ordinality: bool = False


@dataclasses.dataclass(frozen=True)
class ArrayLiteral(Node):
    """ARRAY[e1, e2, ...] (reference: sql/tree/ArrayConstructor)."""

    items: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class JoinRelation(Node):
    join_type: str  # inner | left | right | full | cross
    left: Node
    right: Node
    on: Optional[Node] = None


# ----------------------------------------------------------------- queries

@dataclasses.dataclass(frozen=True)
class SelectItem(Node):
    expr: Node  # expression or Star
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class OrderItem(Node):
    expr: Node
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class QuerySpec(Node):
    """One SELECT block (reference: sql/tree/QuerySpecification)."""

    select: Tuple[SelectItem, ...]
    distinct: bool
    from_: Tuple[Node, ...]  # comma-separated relations (implicit cross)
    where: Optional[Node]
    group_by: Tuple[Node, ...]
    having: Optional[Node]
    order_by: Tuple[OrderItem, ...]
    limit: Optional[int]
    offset: int = 0
    # GROUPING SETS / ROLLUP / CUBE: each set = indices into group_by
    # (the union key list); None = plain GROUP BY (reference:
    # sql/tree/GroupBy + GroupingSets/Rollup/Cube elements)
    grouping_sets: Optional[Tuple[Tuple[int, ...], ...]] = None


@dataclasses.dataclass(frozen=True)
class SetOp(Node):
    """UNION [ALL] / INTERSECT / EXCEPT chains."""

    op: str  # union | union_all | intersect | except
    left: Node
    right: Node


@dataclasses.dataclass(frozen=True)
class With(Node):
    name: str
    column_names: Tuple[str, ...]
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Query(Node):
    """Top: optional WITH list + body (QuerySpec or SetOp) + query-level
    ORDER BY/LIMIT/OFFSET (SQL binds these to the whole body, including
    across UNION branches — the QuerySpec never owns them)."""

    body: Node
    withs: Tuple[With, ...] = ()
    order_by: Tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0


@dataclasses.dataclass(frozen=True)
class Explain(Node):
    query: Query
    analyze: bool = False


@dataclasses.dataclass(frozen=True)
class CreateTableAs(Node):
    """CREATE TABLE [catalog.]name AS query (reference:
    sql/tree/CreateTableAsSelect.java)."""

    parts: Tuple[str, ...]
    query: Query
    replace: bool = False


@dataclasses.dataclass(frozen=True)
class InsertInto(Node):
    """INSERT INTO [catalog.]name query (reference: sql/tree/Insert)."""

    parts: Tuple[str, ...]
    query: Query


@dataclasses.dataclass(frozen=True)
class DropTable(Node):
    parts: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class CreateView(Node):
    """CREATE [OR REPLACE] VIEW name AS query (reference:
    sql/tree/CreateView). Like the reference, the view is stored as its
    SQL text and expanded at analysis time (Analyzer view expansion),
    so it always reflects current base-table data."""

    parts: Tuple[str, ...]
    query_sql: str
    replace: bool = False


@dataclasses.dataclass(frozen=True)
class DropView(Node):
    parts: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Prepare(Node):
    """PREPARE name FROM statement (reference: sql/tree/Prepare; the
    statement text is stored per session and re-planned at EXECUTE
    with parameters bound)."""

    name: str
    statement_sql: str


@dataclasses.dataclass(frozen=True)
class ExecutePrepared(Node):
    """EXECUTE name [USING expr, ...] (reference: sql/tree/Execute).
    arg_sqls carries each argument's raw source text so parameters can
    substitute into DML statements whose predicates/assignments ride as
    raw SQL slices (Delete/Update below)."""

    name: str
    args: Tuple[Node, ...] = ()
    arg_sqls: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Deallocate(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class Parameter(Node):
    """A ? placeholder (reference: sql/tree/Parameter), bound by
    EXECUTE ... USING."""

    index: int


@dataclasses.dataclass(frozen=True)
class Delete(Node):
    """DELETE FROM [catalog.]name [WHERE pred] (reference:
    sql/tree/Delete). The predicate rides as raw SQL — the engine
    rewrites DML into a SELECT of the surviving rows + table replace
    (columnar stores rewrite, they don't mutate in place)."""

    parts: Tuple[str, ...]
    where_sql: Optional[str]


@dataclasses.dataclass(frozen=True)
class Update(Node):
    """UPDATE [catalog.]name SET col = expr, ... [WHERE pred]
    (reference: sql/tree/Update); same rewrite-through-SELECT model."""

    parts: Tuple[str, ...]
    assignments: Tuple[Tuple[str, str], ...]  # (column, raw expr sql)
    where_sql: Optional[str]


@dataclasses.dataclass(frozen=True)
class SetSession(Node):
    """SET SESSION name = value (reference: sql/tree/SetSession)."""

    name: str
    value: object


@dataclasses.dataclass(frozen=True)
class ShowSession(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowTables(Node):
    catalog: Optional[str] = None
