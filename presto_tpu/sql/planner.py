"""SQL planner: typed AST lowering straight to physical plans.

Reference: presto-main sql/analyzer/* (StatementAnalyzer/ExpressionAnalyzer
name+type resolution) + sql/planner/* (RelationPlanner/QueryPlanner building
the PlanNode tree, then PlanOptimizers). Because our plan space is narrower,
the passes the reference runs separately are folded into one lowering:

  - predicate pushdown: WHERE conjuncts referencing one relation filter that
    relation's scan directly (reference: optimizations/PredicatePushDown);
  - column pruning: scans read only referenced columns (reference:
    PruneUnreferencedOutputs);
  - join-key extraction + join ordering: equality conjuncts become hash-join
    edges; a greedy left-deep tree keeps the largest relation as probe side
    and joins the smallest connected relation next (reference: AddExchanges'
    distribution choice + join reordering, heuristic here);
  - OR factoring: conjuncts common to every OR branch are hoisted so queries
    like TPC-H Q19 still get their join keys;
  - subquery decorrelation (reference: sql/planner/SubqueryPlanner +
    TransformCorrelated* rules):
      * uncorrelated scalar -> eager execution, result inlined as a literal
      * correlated scalar aggregate -> group-by over correlation keys joined
        back to the outer side (Q2/Q17/Q20)
      * [NOT] IN / equality-correlated [NOT] EXISTS -> semi/anti join
      * EXISTS with extra correlated predicates -> unique-id join +
        distinct + semi join (general fallback; Q21)

Divergence note: long-decimal (p>18) aggregate results are cast to DOUBLE
when consumed by further expressions (the reference does exact decimal(38)
arithmetic; our exactness boundary is the 2^53 mantissa — far above TPC-H
group sums at validated scales).
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from presto_tpu import types as T
from presto_tpu.exec import agg_states as AS
from presto_tpu.exec import plan as P
from presto_tpu.expr import ir
from presto_tpu.expr import functions as F
from presto_tpu.ops.sort import SortKey
from presto_tpu.sql import ast_nodes as N

AGG_FUNCTIONS = {"sum", "count", "avg", "min", "max", "any_value",
                 "bool_or", "bool_and",
                 "stddev", "stddev_samp", "stddev_pop",
                 "variance", "var_samp", "var_pop",
                 "approx_distinct",
                 "array_agg", "map_agg", "approx_percentile"}

# SQL-surface aliases -> agg_states layout names (reference:
# FunctionRegistry registers stddev as an alias of stddev_samp)
_AGG_CANON = {"stddev": "stddev_samp", "variance": "var_samp",
              "any_value": "any"}


def _canon_agg(name: str) -> str:
    return _AGG_CANON.get(name, name)


def _is_agg_name(name: str) -> bool:
    """Builtin aggregates plus plugin-registered ones (reference:
    FunctionRegistry resolution spanning builtins and plugins)."""
    return name in AGG_FUNCTIONS or AS.is_plugin_aggregate(name)


def _extract_unnests(item: N.Node):
    """Peel UNNEST relations off a FROM item: returns (base relation or
    None, [(UnnestRelation, column_aliases), ...])."""
    if isinstance(item, N.UnnestRelation):
        return None, [(item, ())]
    if isinstance(item, N.AliasedRelation) and isinstance(
        item.relation, N.UnnestRelation
    ):
        return None, [(item.relation, tuple(item.column_aliases))]
    if isinstance(item, N.JoinRelation) and item.join_type == "cross":
        rbase, runs = _extract_unnests(item.right)
        if rbase is None and runs:
            lbase, lruns = _extract_unnests(item.left)
            return lbase, lruns + runs
    return item, []

_EPOCH = datetime.date(1970, 1, 1)


class PlanningError(ValueError):
    pass


class UnresolvedColumnError(PlanningError):
    """A column name resolved in no scope. Distinguished from other planning
    failures so correlation detection (_is_correlated) keys on *this* error
    only — an uncorrelated subquery using an unsupported feature must surface
    its real error, not be misrouted into the correlated decorrelator."""

    def __init__(self, ident):
        super().__init__(f"column not found: {'.'.join(ident.parts)}")
        self.ident = ident


@dataclasses.dataclass(frozen=True)
class OuterRef(ir.RowExpression):
    """Planning-only placeholder for a correlated column (resolved in an
    enclosing scope). Never reaches the evaluator."""

    channel: int
    type: T.SqlType

    def __repr__(self):
        return f"outer#{self.channel}:{self.type}"


@dataclasses.dataclass(frozen=True)
class Field:
    name: Optional[str]
    type: T.SqlType
    qualifiers: frozenset = frozenset()


@dataclasses.dataclass
class RelationPlan:
    node: P.PhysicalNode
    fields: List[Field]

    @property
    def channels(self) -> int:
        return len(self.fields)


class Scope:
    def __init__(self, fields: List[Field], parent: Optional["Scope"] = None):
        self.fields = fields
        self.parent = parent

    def resolve(self, ident: N.Identifier) -> Tuple[int, int, Field]:
        """Returns (level, channel, field); level 0 = this scope."""
        matches = []
        for ch, f in enumerate(self.fields):
            if f.name != ident.name:
                continue
            if ident.qualifier and ident.qualifier not in f.qualifiers:
                continue
            matches.append((ch, f))
        if len(matches) > 1:
            raise PlanningError(f"ambiguous column: {'.'.join(ident.parts)}")
        if matches:
            return 0, matches[0][0], matches[0][1]
        if self.parent is not None:
            lvl, ch, f = self.parent.resolve(ident)
            return lvl + 1, ch, f
        raise UnresolvedColumnError(ident)

    def can_resolve(self, ident: N.Identifier) -> bool:
        try:
            self.resolve(ident)
            return True
        except PlanningError:
            return False


# --------------------------------------------------------------- utilities

def split_conjuncts(e: Optional[N.Node]) -> List[N.Node]:
    if e is None:
        return []
    if isinstance(e, N.BinaryOp) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def split_disjuncts(e: N.Node) -> List[N.Node]:
    if isinstance(e, N.BinaryOp) and e.op == "or":
        return split_disjuncts(e.left) + split_disjuncts(e.right)
    return [e]


def hoist_or_conjuncts(conjuncts: List[N.Node]) -> List[N.Node]:
    """Factor conjuncts common to all OR branches out of the OR (gives Q19
    its p_partkey = l_partkey join key)."""
    out: List[N.Node] = []
    for c in conjuncts:
        branches = split_disjuncts(c)
        if len(branches) < 2:
            out.append(c)
            continue
        branch_sets = [split_conjuncts(b) for b in branches]
        common = [x for x in branch_sets[0]
                  if all(x in bs for bs in branch_sets[1:])]
        if not common:
            out.append(c)
            continue
        out.extend(common)
        rests = []
        for bs in branch_sets:
            rest = [x for x in bs if x not in common]
            rests.append(_and_all(rest))
        residual = _or_all([r for r in rests if r is not None])
        if any(r is None for r in rests):
            residual = None  # one branch fully covered => OR is implied
        if residual is not None:
            out.append(residual)
    return out


def _and_all(items: List[N.Node]) -> Optional[N.Node]:
    if not items:
        return None
    e = items[0]
    for x in items[1:]:
        e = N.BinaryOp("and", e, x)
    return e


def _or_all(items: List[N.Node]) -> Optional[N.Node]:
    if not items:
        return None
    e = items[0]
    for x in items[1:]:
        e = N.BinaryOp("or", e, x)
    return e


def expr_refs(e: ir.RowExpression) -> Set[int]:
    out: Set[int] = set()

    def walk(x):
        if isinstance(x, ir.InputRef):
            out.add(x.channel)
        for c in x.children():
            walk(c)

    walk(e)
    return out


def has_outer_refs(e: ir.RowExpression) -> bool:
    if isinstance(e, OuterRef):
        return True
    return any(has_outer_refs(c) for c in e.children())


def remap_expr(e: ir.RowExpression, mapping: Callable[[int], int]):
    if isinstance(e, ir.InputRef):
        return ir.InputRef(mapping(e.channel), e.type)
    if isinstance(e, OuterRef):
        return e
    if isinstance(e, ir.Call):
        return ir.Call(e.name, tuple(remap_expr(a, mapping) for a in e.args),
                       e.type)
    if isinstance(e, ir.SpecialForm):
        return ir.SpecialForm(
            e.form, tuple(remap_expr(a, mapping) for a in e.args), e.type
        )
    return e


def outer_to_input(e: ir.RowExpression, offset_outer: int, offset_inner: int):
    """Rewrite a correlated predicate for a joined (outer ++ inner) layout."""
    if isinstance(e, OuterRef):
        return ir.InputRef(e.channel + offset_outer, e.type)
    if isinstance(e, ir.InputRef):
        return ir.InputRef(e.channel + offset_inner, e.type)
    if isinstance(e, ir.Call):
        return ir.Call(
            e.name,
            tuple(outer_to_input(a, offset_outer, offset_inner)
                  for a in e.args),
            e.type,
        )
    if isinstance(e, ir.SpecialForm):
        return ir.SpecialForm(
            e.form,
            tuple(outer_to_input(a, offset_outer, offset_inner)
                  for a in e.args),
            e.type,
        )
    return e


def _agg_capacity(node: P.PhysicalNode, catalogs) -> int:
    """Static group-capacity estimate for an aggregation input (reference:
    the pre-CBO source-size heuristics): distinct groups <= input rows,
    clamped to a sane ceiling. Avoids overflow-retry re-runs on
    high-cardinality keys (GROUP BY orderkey) while keeping small
    aggregations small."""
    from presto_tpu.dist.fragmenter import est_rows

    try:
        est = est_rows(node, catalogs)
    except Exception:  # noqa: BLE001 - estimation must never fail
        est = 1 << 16  # planning; unknown shapes get a default
    return max(4096, min(int(est), 1 << 22))


def find_windows(e: N.Node) -> List[N.FunctionCall]:
    """Windowed function calls (fn(...) OVER ...) in an expression, not
    crossing subquery boundaries."""
    out: List[N.FunctionCall] = []

    def walk(x):
        if isinstance(x, N.Query):
            return
        if isinstance(x, N.FunctionCall) and x.window is not None:
            out.append(x)
            return
        for f in (
            dataclasses.fields(x) if dataclasses.is_dataclass(x) else []
        ):
            v = getattr(x, f.name)
            if isinstance(v, N.Node):
                walk(v)
            elif isinstance(v, tuple):
                for item in v:
                    if isinstance(item, N.Node):
                        walk(item)

    walk(e)
    return out


def find_aggregates(e: N.Node) -> List[N.FunctionCall]:
    """Aggregate calls in an AST expression (not nested in another agg and
    not inside a subquery — those belong to the inner SELECT)."""
    out: List[N.FunctionCall] = []

    def walk(x):
        if isinstance(x, N.Query):
            return  # subquery boundary: its aggregates are its own
        if isinstance(x, N.FunctionCall) and x.window is not None:
            # windowed call: not an aggregate; its args/spec may still
            # contain real aggregates (rank() over (order by sum(x)))
            for a in x.args:
                walk(a)
            for p in x.window.partition_by:
                walk(p)
            for o in x.window.order_by:
                walk(o.expr)
            return
        if isinstance(x, N.FunctionCall) and (
            _is_agg_name(x.name) or x.is_star
        ):
            out.append(x)
            return
        for f in dataclasses.fields(x) if dataclasses.is_dataclass(x) else []:
            v = getattr(x, f.name)
            if isinstance(v, N.Node):
                walk(v)
            elif isinstance(v, tuple):
                for item in v:
                    if isinstance(item, N.Node):
                        walk(item)
                    elif (isinstance(item, tuple) and len(item) == 2
                          and isinstance(item[0], N.Node)):
                        walk(item[0])
                        walk(item[1])

    walk(e)
    return out


_BINOP_FN = {
    "+": "add", "-": "subtract", "*": "multiply", "/": "divide",
    "%": "modulus", "=": "eq", "<>": "ne", "<": "lt", "<=": "le",
    ">": "gt", ">=": "ge",
}


# ----------------------------------------------------------------- planner


class Planner:
    """One instance per statement (reference: LogicalPlanner +
    LocalExecutionPlanner collapsed; symbol allocation is implicit in
    channel layouts)."""

    def __init__(
        self,
        catalogs: Dict[str, object],
        default_catalog: str = "tpch",
        scalar_executor: Optional[Callable[[P.PhysicalNode], list]] = None,
        scalar_cache: Optional[Dict] = None,
        views: Optional[Dict] = None,
    ):
        self.catalogs = catalogs
        self.default_catalog = default_catalog
        self.scalar_executor = scalar_executor
        self.ctes: Dict[str, RelationPlan] = {}
        # (catalog, name) -> view SQL text, expanded at analysis like the
        # reference (Analyzer view expansion over sql/tree/CreateView)
        self.views: Dict = views if views is not None else {}
        self._expanding_views: set = set()
        # memoizes executed scalar subqueries per Query node so correlation
        # probes and repeated translation don't re-run them
        self.scalar_cache: Dict = scalar_cache if scalar_cache is not None \
            else {}

    # --------------------------------------------------------- statements
    def plan_statement(self, stmt: N.Node) -> P.Output:
        if isinstance(stmt, N.Explain):
            raise PlanningError("EXPLAIN is handled by the runner")
        if not isinstance(stmt, N.Query):
            raise PlanningError(f"unsupported statement: {type(stmt)}")
        rp, names = self.plan_query_named(stmt, None)
        return P.Output(rp.node, tuple(names))

    def plan_query_named(self, q: N.Query, outer: Optional[Scope]):
        rp = self.plan_query(q, outer)
        names = [f.name or f"_col{i}" for i, f in enumerate(rp.fields)]
        return rp, names

    def plan_query(self, q: N.Query, outer: Optional[Scope]) -> RelationPlan:
        saved = dict(self.ctes)
        try:
            for w in q.withs:
                sub = self.plan_query(w.query, outer)
                fields = sub.fields
                if w.column_names:
                    if len(w.column_names) != len(fields):
                        raise PlanningError(
                            f"WITH {w.name}: column alias count mismatch"
                        )
                    fields = [
                        Field(nm, f.type, frozenset({w.name}))
                        for nm, f in zip(w.column_names, fields)
                    ]
                else:
                    fields = [
                        Field(f.name, f.type, frozenset({w.name}))
                        for f in fields
                    ]
                self.ctes[w.name] = RelationPlan(sub.node, fields)
            body = q.body
            if isinstance(body, N.QuerySpec):
                rp = self.plan_query_spec(body, outer)
            elif isinstance(body, N.SetOp):
                rp = self.plan_set_op(body, outer)
            elif isinstance(body, N.Query):
                rp = self.plan_query(body, outer)
            else:
                raise PlanningError(f"unsupported query body: {type(body)}")
            if q.order_by:
                keys = self._order_keys(q.order_by, rp)
                if q.limit is not None and not q.offset:
                    rp = RelationPlan(P.TopN(rp.node, keys, q.limit),
                                      rp.fields)
                else:
                    rp = RelationPlan(P.Sort(rp.node, keys), rp.fields)
                    if q.limit is not None:
                        rp = RelationPlan(
                            P.Limit(rp.node, q.limit, q.offset), rp.fields
                        )
            elif q.limit is not None:
                rp = RelationPlan(P.Limit(rp.node, q.limit, q.offset),
                                  rp.fields)
            return rp
        finally:
            self.ctes = saved

    def plan_set_op(self, s: N.SetOp, outer: Optional[Scope]) -> RelationPlan:
        left = self._plan_term(s.left, outer)
        right = self._plan_term(s.right, outer)
        if left.channels != right.channels:
            raise PlanningError("set operation column count mismatch")
        if s.op in ("union_all", "union"):
            # coerce branches to common column types (reference: the
            # analyzer's setop type coercion)
            common = []
            for lf, rf in zip(left.fields, right.fields):
                ct = T.common_super_type(lf.type, rf.type)
                if ct is None:
                    raise PlanningError(
                        f"UNION column types incompatible: {lf.type} vs "
                        f"{rf.type}"
                    )
                common.append(ct)

            def coerce(rp: RelationPlan) -> RelationPlan:
                if all(f.type == c for f, c in zip(rp.fields, common)):
                    return rp
                exprs = tuple(
                    ir.InputRef(i, f.type) if f.type == c
                    else ir.cast(ir.InputRef(i, f.type), c)
                    for i, (f, c) in enumerate(zip(rp.fields, common))
                )
                return RelationPlan(
                    P.Project(rp.node, exprs),
                    [Field(f.name, c, f.qualifiers)
                     for f, c in zip(rp.fields, common)],
                )

            left = coerce(left)
            right = coerce(right)
            node = P.Union((left.node, right.node))
            rp = RelationPlan(node, left.fields)
            if s.op == "union":
                rp = RelationPlan(
                    P.Aggregation(
                        rp.node, tuple(range(rp.channels)), (),
                        capacity=_agg_capacity(rp.node, self.catalogs),
                    ),
                    rp.fields,
                )
            return rp
        raise PlanningError(f"unsupported set operation: {s.op}")

    def _plan_term(self, t: N.Node, outer):
        if isinstance(t, N.QuerySpec):
            return self.plan_query_spec(t, outer)
        if isinstance(t, N.Query):
            return self.plan_query(t, outer)
        if isinstance(t, N.SetOp):
            return self.plan_set_op(t, outer)
        raise PlanningError(f"unsupported set operand: {type(t)}")

    # ---------------------------------------------------------- relations
    def plan_relation(self, rel: N.Node, outer: Optional[Scope]):
        if isinstance(rel, N.Table):
            return self._plan_table(rel)
        if isinstance(rel, N.AliasedRelation):
            rp = self.plan_relation(rel.relation, outer)
            names = (
                list(rel.column_aliases)
                if rel.column_aliases
                else [f.name for f in rp.fields]
            )
            if len(names) != len(rp.fields):
                raise PlanningError("column alias count mismatch")
            fields = [
                Field(nm, f.type, frozenset({rel.alias}))
                for nm, f in zip(names, rp.fields)
            ]
            return RelationPlan(rp.node, fields)
        if isinstance(rel, N.SubqueryRelation):
            rp, names = self.plan_query_named(rel.query, outer)
            fields = [
                Field(nm, f.type, frozenset())
                for nm, f in zip(names, rp.fields)
            ]
            return RelationPlan(rp.node, fields)
        if isinstance(rel, N.JoinRelation):
            return self._plan_explicit_join(rel, outer)
        raise PlanningError(f"unsupported relation: {type(rel)}")

    def _plan_table(self, rel: N.Table) -> RelationPlan:
        parts = rel.parts
        name = parts[-1]
        if len(parts) == 1 and name in self.ctes:
            cte = self.ctes[name]
            return RelationPlan(cte.node, list(cte.fields))
        catalog = self.default_catalog
        if len(parts) >= 2 and parts[0] in self.catalogs:
            catalog = parts[0]
        view_sql = self.views.get((catalog, name))
        if view_sql is not None:
            return self._expand_view(catalog, name, view_sql)
        conn = self.catalogs.get(catalog)
        if conn is None:
            raise PlanningError(f"unknown catalog: {catalog}")
        try:
            schema = conn.table_schema(name)
        except KeyError:
            raise PlanningError(f"table not found: {name}")
        cols = tuple(schema.column_names())
        fields = [
            Field(c.name, c.type, frozenset({name}))
            for c in schema.columns
        ]
        return RelationPlan(P.TableScan(catalog, name, cols), fields)

    def _expand_view(self, catalog: str, name: str,
                     view_sql: str) -> RelationPlan:
        """Reference: StatementAnalyzer view expansion — the stored SQL
        re-analyzes against current metadata; cycles are an error. The
        view body must not see the referencing query's CTEs."""
        from presto_tpu.sql.parser import parse as _parse

        key = (catalog, name)
        if key in self._expanding_views:
            raise PlanningError(f"view cycle detected at {name!r}")
        self._expanding_views.add(key)
        saved_ctes = self.ctes
        self.ctes = {}
        try:
            q = _parse(view_sql)
            rp, names = self.plan_query_named(q, None)
        finally:
            self.ctes = saved_ctes
            self._expanding_views.discard(key)
        fields = [
            Field(nm, f.type, frozenset({name}))
            for nm, f in zip(names, rp.fields)
        ]
        return RelationPlan(rp.node, fields)

    def _plan_explicit_join(self, rel: N.JoinRelation, outer):
        left = self.plan_relation(rel.left, outer)
        right = self.plan_relation(rel.right, outer)
        if rel.join_type == "cross":
            return RelationPlan(
                P.CrossJoin(left.node, right.node), left.fields + right.fields
            )
        on = rel.on
        scope = Scope(left.fields + right.fields, outer)
        nleft = left.channels
        if isinstance(on, tuple) and on[0] == "using":
            return self._plan_using_join(rel, left, right, on[1])
        conjuncts = split_conjuncts(on)
        tr = ExprTranslator(self, scope)
        left_keys: List[int] = []
        right_keys: List[int] = []
        left_filters: List[ir.RowExpression] = []
        right_filters: List[ir.RowExpression] = []
        residual: List[ir.RowExpression] = []
        for c in conjuncts:
            e = tr.translate(c)
            refs = expr_refs(e)
            if (
                isinstance(e, ir.Call) and e.name == "eq"
                and isinstance(e.args[0], ir.InputRef)
                and isinstance(e.args[1], ir.InputRef)
            ):
                a, b = e.args[0].channel, e.args[1].channel
                if a < nleft <= b:
                    left_keys.append(a)
                    right_keys.append(b - nleft)
                    continue
                if b < nleft <= a:
                    left_keys.append(b)
                    right_keys.append(a - nleft)
                    continue
            if refs and max(refs) < nleft:
                left_filters.append(e)
                continue
            if refs and min(refs) >= nleft:
                right_filters.append(
                    remap_expr(e, lambda ch: ch - nleft)
                )
                continue
            residual.append(e)
        jt = rel.join_type
        # single-side ON filters: for outer joins they scope the *join*,
        # not the preserved side; pushing into the non-preserved side is
        # equivalent (reference: PredicatePushDown's outer join handling)
        if left_filters:
            if jt in ("inner", "right"):
                left = RelationPlan(
                    P.Filter(left.node, _and_ir(left_filters)), left.fields
                )
            else:
                raise PlanningError(
                    "ON predicate over the preserved side of an outer join "
                    "is not supported yet"
                )
        if right_filters:
            if jt in ("inner", "left"):
                right = RelationPlan(
                    P.Filter(right.node, _and_ir(right_filters)), right.fields
                )
            else:
                raise PlanningError(
                    "ON predicate over the preserved side of an outer join "
                    "is not supported yet"
                )
        if not left_keys:
            if jt != "inner":
                raise PlanningError("outer join requires equi-join keys")
            node: P.PhysicalNode = P.CrossJoin(left.node, right.node)
        else:
            node = P.HashJoin(
                left.node, right.node, tuple(left_keys), tuple(right_keys),
                join_type=jt,
            )
        rp = RelationPlan(node, left.fields + right.fields)
        if residual:
            if jt != "inner":
                raise PlanningError(
                    "non-equi ON predicates on outer joins are not "
                    "supported yet"
                )
            rp = RelationPlan(P.Filter(rp.node, _and_ir(residual)), rp.fields)
        return rp

    def _plan_using_join(self, rel, left, right, names) -> RelationPlan:
        """JOIN ... USING (c1, ...): equi-join on same-named columns;
        the output carries ONE copy of each using column (unqualified),
        coalescing the sides for FULL joins, then the remaining columns
        of both sides in order (reference: StatementAnalyzer USING
        output scope rules)."""
        jt = rel.join_type

        def chan(fields, name, side):
            hits = [
                i for i, f in enumerate(fields) if f.name == name
            ]
            if not hits:
                raise PlanningError(
                    f"USING column {name!r} not on the {side} side"
                )
            if len(hits) > 1:
                raise PlanningError(
                    f"USING column {name!r} is ambiguous on the "
                    f"{side} side"
                )
            return hits[0]

        left_keys = tuple(
            chan(left.fields, n, "left") for n in names
        )
        right_keys = tuple(
            chan(right.fields, n, "right") for n in names
        )
        node = P.HashJoin(
            left.node, right.node, left_keys, right_keys, join_type=jt,
        )
        nleft = left.channels
        joined = left.fields + right.fields
        exprs: List[ir.RowExpression] = []
        fields: List[Field] = []
        for n, lk, rk in zip(names, left_keys, right_keys):
            lt = left.fields[lk].type
            rt = right.fields[rk].type
            t = T.common_super_type(lt, rt) or lt
            lref = ir.InputRef(lk, lt)
            rref = ir.InputRef(nleft + rk, rt)
            if jt == "full":
                e = ir.coalesce(lref, rref)
            elif jt == "right":
                e = rref
            else:
                e = lref
            exprs.append(e)
            fields.append(Field(n, t, frozenset()))
        skip_l = set(left_keys)
        skip_r = {nleft + rk for rk in right_keys}
        for i, f in enumerate(joined):
            if i in skip_l or i in skip_r:
                continue
            exprs.append(ir.InputRef(i, f.type))
            fields.append(f)
        return RelationPlan(P.Project(node, tuple(exprs)), fields)

    # ------------------------------------------------------------ costing
    def estimate(self, node: P.PhysicalNode) -> float:
        """Crude cardinality estimate driving join order / side choice
        (reference: the stats calculators feeding AddExchanges; here simple
        selectivity constants)."""
        if isinstance(node, P.TableScan):
            return float(self.catalogs[node.catalog].row_count(node.table))
        if isinstance(node, P.Values):
            return float(len(node.rows))
        if isinstance(node, P.Filter):
            return max(self.estimate(node.source) * 0.33, 1.0)
        if isinstance(node, (P.Project, P.UniqueId, P.Output)):
            return self.estimate(node.children()[0])
        if isinstance(node, P.Aggregation):
            return max(self.estimate(node.source) / 8.0, 1.0)
        if isinstance(node, P.HashJoin):
            if node.join_type in ("semi", "anti"):
                return self.estimate(node.left)
            return max(self.estimate(node.left), self.estimate(node.right))
        if isinstance(node, P.CrossJoin):
            return self.estimate(node.left) * self.estimate(node.right)
        if isinstance(node, P.Union):
            return sum(self.estimate(s) for s in node.sources)
        if isinstance(node, (P.Sort, P.TopN, P.Limit)):
            return self.estimate(node.source)
        return 1000.0

    # ------------------------------------------------- FROM + WHERE engine
    def _plan_from_where(
        self,
        spec: N.QuerySpec,
        outer: Optional[Scope],
        collect_correlation: bool,
    ):
        """Plan FROM relations and WHERE; returns (RelationPlan, corr_eqs,
        residual_correlated) where corr_eqs are (outer_channel,
        local_channel) equality pairs when collect_correlation is set.

        UNNEST items are lateral: they are peeled off the FROM list
        here and applied AFTER the join tree, where their array
        expressions can see every base relation's columns. (WHERE
        conjuncts cannot reference UNNEST outputs in this version —
        filter in an enclosing query.)"""
        base_items: List[N.Node] = []
        unnests: List[Tuple[N.UnnestRelation, tuple]] = []
        for item in spec.from_:
            b, us = _extract_unnests(item)
            if b is not None:
                base_items.append(b)
            unnests.extend(us)
        if not base_items:
            rp = RelationPlan(P.Values((T.BIGINT,), ((0,),)),
                              [Field(None, T.BIGINT)])
            units = [rp]
        else:
            units = [self.plan_relation(r, outer) for r in base_items]

        offsets = []
        total = 0
        all_fields: List[Field] = []
        for u in units:
            offsets.append(total)
            total += u.channels
            all_fields.extend(u.fields)
        scope = Scope(all_fields, outer)
        tr = ExprTranslator(self, scope)

        conjuncts = hoist_or_conjuncts(split_conjuncts(spec.where))

        unit_filters: Dict[int, List[ir.RowExpression]] = {}
        edges: List[Tuple[int, int, int, int]] = []  # (ui, ci, uj, cj)
        post: List[ir.RowExpression] = []
        corr_eqs: List[Tuple[int, int]] = []  # (outer_ch, combined_ch)
        corr_residual: List[ir.RowExpression] = []
        subplans: List[Tuple[str, object]] = []  # deferred subquery preds

        def unit_of(ch: int) -> int:
            for i in range(len(units) - 1, -1, -1):
                if ch >= offsets[i]:
                    return i
            return 0

        for c in conjuncts:
            handled = self._try_subquery_conjunct(c, scope, subplans)
            if handled:
                continue
            e = tr.translate(c)
            if has_outer_refs(e):
                if not collect_correlation:
                    raise PlanningError(
                        "correlated reference outside a subquery"
                    )
                if (
                    isinstance(e, ir.Call) and e.name == "eq"
                    and isinstance(e.args[0], ir.InputRef)
                    and isinstance(e.args[1], OuterRef)
                ):
                    corr_eqs.append((e.args[1].channel, e.args[0].channel))
                elif (
                    isinstance(e, ir.Call) and e.name == "eq"
                    and isinstance(e.args[1], ir.InputRef)
                    and isinstance(e.args[0], OuterRef)
                ):
                    corr_eqs.append((e.args[0].channel, e.args[1].channel))
                else:
                    corr_residual.append(e)
                continue
            refs = expr_refs(e)
            ref_units = {unit_of(ch) for ch in refs}
            if (
                isinstance(e, ir.Call) and e.name == "eq"
                and isinstance(e.args[0], ir.InputRef)
                and isinstance(e.args[1], ir.InputRef)
                and len(ref_units) == 2
            ):
                ui = unit_of(e.args[0].channel)
                uj = unit_of(e.args[1].channel)
                edges.append((
                    ui, e.args[0].channel - offsets[ui],
                    uj, e.args[1].channel - offsets[uj],
                ))
                continue
            if len(ref_units) <= 1:
                u = next(iter(ref_units)) if ref_units else 0
                unit_filters.setdefault(u, []).append(
                    remap_expr(e, lambda ch, o=offsets[u]: ch - o)
                )
                continue
            post.append(e)

        for u, filters in unit_filters.items():
            units[u] = RelationPlan(
                P.Filter(units[u].node, _and_ir(filters)), units[u].fields
            )

        plan, layout = self._build_join_tree(units, edges)

        def final_ch(combined_ch: int) -> int:
            u = unit_of(combined_ch)
            return layout[u] + (combined_ch - offsets[u])

        post = [remap_expr(e, final_ch) for e in post]
        corr_eqs = [(o, final_ch(c)) for o, c in corr_eqs]
        corr_residual = [remap_expr(e, final_ch) for e in corr_residual]

        # deferred subquery predicates (IN / EXISTS / scalar comparisons)
        for kind, payload in subplans:
            plan, extra = self._apply_subquery_pred(
                plan, kind, payload, final_ch
            )
            post.extend(extra)

        if post:
            plan = RelationPlan(
                P.Filter(plan.node, _and_ir(post)), plan.fields
            )

        # lateral UNNEST expansion over the joined relation
        for un, cols in unnests:
            tr2 = ExprTranslator(self, Scope(plan.fields, outer))
            e = tr2.translate(un.expr)
            if not isinstance(e.type, T.ArrayType):
                raise PlanningError(
                    f"UNNEST requires an array-typed expression, got "
                    f"{e.type}"
                )
            ch = self._append_channel(plan, e)
            elem_t = e.type.element
            plan.node = P.Unnest(plan.node, ch, elem_t,
                                 un.with_ordinality)
            plan.fields = plan.fields + [
                Field(cols[0] if cols else None, elem_t)
            ]
            if un.with_ordinality:
                plan.fields = plan.fields + [
                    Field(cols[1] if len(cols) > 1 else "ordinality",
                          T.BIGINT)
                ]
        return plan, corr_eqs, corr_residual

    def _try_subquery_conjunct(self, c: N.Node, scope: Scope,
                               subplans: list) -> bool:
        if isinstance(c, N.Exists):
            subplans.append(("exists", (c.query, c.negated, scope)))
            return True
        if isinstance(c, N.UnaryOp) and c.op == "not" and isinstance(
                c.operand, N.Exists):
            subplans.append(
                ("exists", (c.operand.query, not c.operand.negated, scope))
            )
            return True
        if isinstance(c, N.InSubquery):
            subplans.append(("in", (c.value, c.query, c.negated, scope)))
            return True
        if isinstance(c, N.BinaryOp) and c.op in (
                "=", "<>", "<", "<=", ">", ">="):
            for side, other in ((c.left, c.right), (c.right, c.left)):
                if isinstance(side, N.ScalarSubquery):
                    if self._is_correlated(side.query, scope):
                        subplans.append(
                            ("scalar_corr", (other, c.op, side.query,
                                             side is c.left, scope))
                        )
                        return True
                    return False  # uncorrelated: inline via translator
        return False

    def _is_correlated(self, q: N.Query, scope: Scope) -> bool:
        """Correlated iff planning without an outer scope hits an unresolved
        column that DOES resolve in the outer scope. Any other planning
        failure is a genuine error in the subquery and propagates as-is
        (ADVICE r1: inferring correlation from arbitrary PlanningErrors sent
        unsupported-feature errors into the decorrelator's misleading
        'must be a single aggregate' path)."""
        try:
            self._plan_uncorrelated_probe(q)
            return False
        except UnresolvedColumnError as err:
            if scope is not None and scope.can_resolve(err.ident):
                return True
            raise

    def _plan_uncorrelated_probe(self, q: N.Query):
        # planning without an outer scope raises on correlated refs
        sub = Planner(self.catalogs, self.default_catalog,
                      self.scalar_executor,
                      scalar_cache=self.scalar_cache)
        sub.ctes = dict(self.ctes)
        return sub.plan_query(q, None)

    def _apply_subquery_pred(self, plan: RelationPlan, kind: str, payload,
                             final_ch):
        """Attach a subquery predicate to the built join tree. Channels are
        append-only so previously-translated expressions stay valid."""
        extra: List[ir.RowExpression] = []
        if kind == "in":
            value_ast, query, negated, _scope = payload
            scope = Scope(plan.fields)
            tr = ExprTranslator(self, scope)
            value = tr.translate(value_ast)
            if has_outer_refs(value):
                raise PlanningError("correlated IN value not supported")
            sub = self.plan_query(query, None)
            if sub.channels != 1:
                raise PlanningError("IN subquery must produce one column")
            probe_ch = self._append_channel(plan, value)
            plan = RelationPlan(
                P.HashJoin(plan.node, sub.node, (probe_ch,), (0,),
                           join_type="semi"),
                plan.fields + [Field(None, T.BOOLEAN)],
            )
            match = ir.InputRef(plan.channels - 1, T.BOOLEAN)
            extra.append(ir.not_(match) if negated else match)
            return plan, extra
        if kind == "exists":
            query, negated, _scope = payload
            outer_scope = Scope(plan.fields)
            spec = _query_to_spec(query)
            if spec.group_by or spec.having is not None or any(
                find_aggregates(i.expr)
                for i in spec.select
                if not isinstance(i.expr, N.Star)
            ):
                raise PlanningError(
                    "EXISTS over aggregated/grouped subqueries is not "
                    "supported yet"
                )
            inner, corr_eqs, corr_residual = self._plan_from_where(
                spec, outer_scope, collect_correlation=True
            )
            if not corr_eqs:
                raise PlanningError(
                    "uncorrelated EXISTS not supported yet"
                )
            if not corr_residual:
                outer_keys = tuple(o for o, _ in corr_eqs)
                inner_keys = tuple(i for _, i in corr_eqs)
                plan = RelationPlan(
                    P.HashJoin(plan.node, inner.node, outer_keys, inner_keys,
                               join_type="semi"),
                    plan.fields + [Field(None, T.BOOLEAN)],
                )
                match = ir.InputRef(plan.channels - 1, T.BOOLEAN)
                extra.append(ir.not_(match) if negated else match)
                return plan, extra
            # general fallback (Q21): unique-id join + distinct + semi
            with_id = RelationPlan(
                P.UniqueId(plan.node), plan.fields + [Field(None, T.BIGINT)]
            )
            id_ch = with_id.channels - 1
            n_outer = with_id.channels
            join = P.HashJoin(
                with_id.node, inner.node,
                tuple(o for o, _ in corr_eqs),
                tuple(i for _, i in corr_eqs),
                join_type="inner",
            )
            preds = [
                outer_to_input(e, 0, n_outer) for e in corr_residual
            ]
            filt = P.Filter(join, _and_ir(preds))
            dedup_src = P.Project(filt, (ir.InputRef(id_ch, T.BIGINT),))
            matched_ids = P.Aggregation(
                dedup_src, (0,), (),
                capacity=_agg_capacity(dedup_src, self.catalogs),
            )
            plan = RelationPlan(
                P.HashJoin(with_id.node, matched_ids, (id_ch,), (0,),
                           join_type="semi"),
                with_id.fields + [Field(None, T.BOOLEAN)],
            )
            match = ir.InputRef(plan.channels - 1, T.BOOLEAN)
            extra.append(ir.not_(match) if negated else match)
            return plan, extra
        if kind == "scalar_corr":
            other_ast, op, query, subquery_is_left, _scope = payload
            outer_scope = Scope(plan.fields)
            spec = _query_to_spec(query)
            if len(spec.select) != 1 or spec.group_by or (
                spec.having is not None
            ):
                raise PlanningError(
                    "correlated scalar subquery must be a single aggregate"
                )
            inner_aggs = find_aggregates(spec.select[0].expr)
            if not inner_aggs:
                raise PlanningError(
                    "correlated scalar subquery must be a single aggregate"
                )
            has_count = any(
                a.is_star or a.name == "count" for a in inner_aggs
            )
            is_count = has_count and spec.select[0].expr in inner_aggs
            if has_count and not is_count:
                raise PlanningError(
                    "correlated scalar subquery computing over count() "
                    "is only supported as a bare count"
                )
            inner, corr_eqs, corr_residual = self._plan_from_where(
                spec, outer_scope, collect_correlation=True
            )
            if corr_residual or not corr_eqs:
                raise PlanningError(
                    "correlated scalar subquery needs pure equality "
                    "correlation"
                )
            # aggregate over correlation keys (classic decorrelation)
            inner_scope = Scope(inner.fields)
            sub, _names = self._plan_aggregation_block(
                inner, inner_scope,
                group_irs=[
                    ir.InputRef(i, inner.fields[i].type)
                    for _, i in corr_eqs
                ],
                select_items=[N.SelectItem(spec.select[0].expr, "value")],
                having=None,
                include_keys=True,
            )
            n_keys = len(corr_eqs)
            base = plan.channels
            # LEFT join: outer rows with no group must survive — for count
            # aggregates SQL defines the subquery value as 0 there, and for
            # min/max/sum/avg the NULL comparison filters the row anyway
            plan = RelationPlan(
                P.HashJoin(
                    plan.node, sub.node,
                    tuple(o for o, _ in corr_eqs),
                    tuple(range(n_keys)),
                    join_type="left",
                ),
                plan.fields + sub.fields,
            )
            tr = ExprTranslator(self, Scope(plan.fields))
            other = tr.translate(other_ast)
            value_ref: ir.RowExpression = ir.InputRef(
                base + n_keys, sub.fields[n_keys].type
            )
            value_ref = _decimal_safe(value_ref)
            if is_count:
                value_ref = ir.coalesce(
                    value_ref, ir.Constant(0, value_ref.type)
                )
            a, b = ((value_ref, other) if subquery_is_left
                    else (other, value_ref))
            extra.append(ir.call(_BINOP_FN[op], a, b))
            return plan, extra
        raise PlanningError(f"unknown subquery kind: {kind}")

    def _append_channel(self, plan: RelationPlan,
                        expr: ir.RowExpression) -> int:
        """Append a computed channel via identity projection; mutates plan
        in place and returns the new channel index."""
        exprs = tuple(
            ir.InputRef(i, f.type) for i, f in enumerate(plan.fields)
        ) + (expr,)
        plan.node = P.Project(plan.node, exprs)
        plan.fields = plan.fields + [Field(None, expr.type)]
        return len(plan.fields) - 1

    def _unit_unique_channels(self, unit: RelationPlan) -> frozenset:
        """Channels of a relation plan that provably carry a unique
        column of the underlying scan (shared walker:
        P.scan_column_unique — the same judgment the executor's join
        sizing makes)."""
        return frozenset(
            ch for ch in range(len(unit.fields))
            if P.scan_column_unique(unit.node, ch, self.catalogs)
        )

    def _build_join_tree(self, units: List[RelationPlan], edges):
        """Greedy left-deep join tree: largest unit is the initial probe;
        repeatedly join the best connected unit as build side
        (reference: AddExchanges partitioned-vs-broadcast + join
        reordering, heuristic form).

        "Best" = SAFE joins first — build keys that include a provably
        unique column of the build unit guarantee <=1 match per probe
        row, so the join can never expand the probe — then smallest
        estimated BYTE footprint (exact generator/table row counts x
        static row width, the same stats the memory governor sizes
        buffers with — a narrow-but-long table no longer beats a
        wide-but-short one for the build side). Without the safety
        term, a small-but-non-unique build (TPC-H Q5's customer joined
        on c_nationkey: 25 distinct values) fans out catastrophically
        at scale even though it looks cheapest."""
        from presto_tpu.exec.executor import _row_bytes

        n = len(units)
        if n == 1:
            return units[0], {0: 0}
        est = [
            self.estimate(u.node) * _row_bytes([f.type for f in u.fields])
            for u in units
        ]
        uniq = [self._unit_unique_channels(u) for u in units]
        start = max(range(n), key=lambda i: est[i])
        placed = {start: 0}
        plan = units[start]
        remaining = set(range(n)) - {start}
        while remaining:

            def candidate_keys(u):
                probe_keys, build_keys = [], []
                for ui, ci, uj, cj in edges:
                    if ui in placed and uj == u:
                        probe_keys.append(placed[ui] + ci)
                        build_keys.append(cj)
                    elif uj in placed and ui == u:
                        probe_keys.append(placed[uj] + cj)
                        build_keys.append(ci)
                return probe_keys, build_keys

            connected = [
                u for u in remaining
                if any(
                    (ui in placed and uj == u) or (uj in placed and ui == u)
                    for ui, _, uj, _ in edges
                )
            ]
            if connected:
                u = min(
                    connected,
                    key=lambda i: (
                        not any(
                            k in uniq[i]
                            for k in candidate_keys(i)[1]
                        ),
                        est[i],
                    ),
                )
                probe_keys, build_keys = candidate_keys(u)
                node = P.HashJoin(
                    plan.node, units[u].node,
                    tuple(probe_keys), tuple(build_keys), join_type="inner",
                )
                placed[u] = plan.channels
                plan = RelationPlan(node, plan.fields + units[u].fields)
            else:
                u = min(remaining, key=lambda i: est[i])
                node = P.CrossJoin(plan.node, units[u].node)
                placed[u] = plan.channels
                plan = RelationPlan(node, plan.fields + units[u].fields)
            remaining.remove(u)
        return plan, placed

    # ------------------------------------------------------ spec planning
    def plan_query_spec(self, spec: N.QuerySpec,
                        outer: Optional[Scope]) -> RelationPlan:
        plan, corr_eqs, corr_residual = self._plan_from_where(
            spec, outer, collect_correlation=outer is not None
        )
        if corr_eqs or corr_residual:
            raise PlanningError(
                "correlated subquery in an unsupported position"
            )
        scope = Scope(plan.fields, outer)

        aggs: List[N.FunctionCall] = []
        for item in spec.select:
            if not isinstance(item.expr, N.Star):
                aggs.extend(find_aggregates(item.expr))
        if spec.having is not None:
            aggs.extend(find_aggregates(spec.having))
        for o in spec.order_by:
            aggs.extend(find_aggregates(o.expr))

        windows: List[N.FunctionCall] = []
        for item in spec.select:
            if not isinstance(item.expr, N.Star):
                windows.extend(find_windows(item.expr))
        for o in spec.order_by:
            windows.extend(find_windows(o.expr))
        if windows and (spec.group_by or aggs):
            raise PlanningError(
                "window functions over aggregations in the same query "
                "block are not supported yet; aggregate in a subquery"
            )

        if windows:
            plan, scope, win_subst = self._plan_windows(
                plan, scope, windows
            )
        else:
            win_subst = {}

        if spec.group_by or aggs:
            tr = ExprTranslator(self, scope)
            group_irs = []
            for g in spec.group_by:
                if isinstance(g, N.Literal) and g.kind == "long":
                    item = spec.select[
                        _ordinal(g.value, len(spec.select), "GROUP BY")
                    ]
                    group_irs.append(tr.translate(item.expr))
                else:
                    group_irs.append(tr.translate(g))
            (plan2, names) = self._plan_aggregation_block(
                plan, scope, group_irs, list(spec.select), spec.having,
                grouping_sets=spec.grouping_sets,
            )
            plan = plan2
        else:
            names = []
            exprs = []
            tr = ExprTranslator(self, scope, agg_subst=win_subst)
            out_fields = []
            for item in spec.select:
                if isinstance(item.expr, N.Star):
                    for ch, f in enumerate(plan.fields):
                        if item.expr.qualifier and (
                            item.expr.qualifier not in f.qualifiers
                        ):
                            continue
                        exprs.append(ir.InputRef(ch, f.type))
                        names.append(f.name)
                        out_fields.append(Field(f.name, f.type))
                    continue
                e = tr.translate(item.expr)
                nm = item.alias or (
                    item.expr.name if isinstance(item.expr, N.Identifier)
                    else None
                )
                exprs.append(e)
                names.append(nm)
                out_fields.append(Field(nm, e.type))
            plan = RelationPlan(P.Project(plan.node, tuple(exprs)),
                                out_fields)

        if spec.distinct:
            plan = RelationPlan(
                P.Aggregation(
                    plan.node, tuple(range(plan.channels)), (),
                    capacity=_agg_capacity(plan.node, self.catalogs),
                ),
                plan.fields,
            )

        # ORDER BY / LIMIT are query-level (plan_query) — the parser never
        # attaches them to a QuerySpec
        return plan

    @staticmethod
    def _check_frame(wspec):
        """Validate an explicit window frame (reference:
        sql/analyzer/WindowFrameAnalyzer rules): ROWS frames take any
        bound; RANGE frames only UNBOUNDED/CURRENT (value-range offsets
        need per-type arithmetic the kernels don't do)."""
        frame = wspec.frame
        if frame is None:
            return None
        unit, (sk, _sn), (ek, _en) = frame
        order = {"unbounded_preceding": 0, "preceding": 1, "current": 2,
                 "following": 3, "unbounded_following": 4}
        if sk == "unbounded_following" or ek == "unbounded_preceding":
            raise PlanningError("invalid window frame bounds")
        if order[sk] > order[ek]:
            raise PlanningError(
                "window frame start cannot follow its end"
            )
        if unit == "range" and (
            sk not in ("unbounded_preceding", "current")
            or ek not in ("current", "unbounded_following")
        ):
            raise PlanningError(
                "RANGE frames support only UNBOUNDED/CURRENT bounds"
            )
        return frame

    def _plan_windows(self, plan, scope, windows):
        """Plan windowed calls over the FROM/WHERE result: pre-project the
        partition/order/argument expressions, add one Window node per
        distinct OVER clause, and return a substitution map call->channel
        for the final projection (reference: QueryPlanner.window +
        WindowNode; execution is ops/window.py's segmented scans)."""
        from presto_tpu.ops import window as W
        from presto_tpu.ops.sort import SortKey

        tr = ExprTranslator(self, scope)
        pre_exprs: List[ir.RowExpression] = [
            ir.InputRef(i, f.type) for i, f in enumerate(plan.fields)
        ]

        def chan_for(ast_expr) -> int:
            # long-decimal window inputs compute in double (the module-
            # docstring long-decimal divergence; ops/window has no limb
            # arithmetic)
            e = _decimal_safe(tr.translate(ast_expr))
            if isinstance(e, ir.InputRef):
                return e.channel
            for i, existing in enumerate(pre_exprs):
                if existing == e:
                    return i
            pre_exprs.append(e)
            return len(pre_exprs) - 1

        # group calls by their OVER clause
        by_spec: Dict[object, List[N.FunctionCall]] = {}
        for call in windows:
            by_spec.setdefault(call.window, [])
            if call not in by_spec[call.window]:
                by_spec[call.window].append(call)

        specs = []
        for wspec, calls in by_spec.items():
            part_chs = tuple(chan_for(p) for p in wspec.partition_by)
            order_keys = tuple(
                SortKey(chan_for(o.expr), o.ascending, o.nulls_first)
                for o in wspec.order_by
            )
            frame = self._check_frame(wspec)
            fns = []
            for call in calls:
                fname = call.name
                arg_ch = None
                offset = 1

                def int_literal(node, what):
                    if not (isinstance(node, N.Literal)
                            and node.kind == "long"):
                        raise PlanningError(
                            f"{what} must be an integer literal"
                        )
                    return int(node.value)

                if fname in ("lag", "lead"):
                    if len(call.args) > 2:
                        raise PlanningError(
                            "lag/lead default argument not supported"
                        )
                    arg_ch = chan_for(call.args[0])
                    if len(call.args) == 2:
                        offset = int_literal(call.args[1],
                                             "lag/lead offset")
                elif fname in ("row_number", "rank", "dense_rank",
                               "percent_rank", "cume_dist"):
                    pass
                elif fname == "ntile":
                    if len(call.args) != 1:
                        raise PlanningError("ntile takes one argument")
                    offset = int_literal(call.args[0], "ntile buckets")
                    if offset < 1:
                        raise PlanningError("ntile buckets must be >= 1")
                elif fname == "nth_value":
                    if len(call.args) != 2:
                        raise PlanningError(
                            "nth_value takes two arguments"
                        )
                    arg_ch = chan_for(call.args[0])
                    offset = int_literal(call.args[1], "nth_value n")
                    if offset < 1:
                        raise PlanningError("nth_value n must be >= 1")
                elif fname in ("count",) and (call.is_star or
                                              not call.args):
                    fname = "count_star"
                elif fname in ("sum", "avg", "min", "max", "count",
                               "first_value", "last_value"):
                    arg_ch = chan_for(call.args[0])
                else:
                    raise PlanningError(
                        f"unsupported window function: {fname}"
                    )
                fns.append(W.WindowFunc(fname, arg_ch, offset,
                                        frame=frame))
            specs.append((part_chs, order_keys, tuple(fns), calls))

        node = plan.node
        if len(pre_exprs) > len(plan.fields) or any(
            not isinstance(e, ir.InputRef) or e.channel != i
            for i, e in enumerate(pre_exprs)
        ):
            node = P.Project(node, tuple(pre_exprs))
        pre_fields = list(plan.fields) + [
            Field(None, e.type) for e in pre_exprs[len(plan.fields):]
        ]

        win_subst: Dict[object, ir.RowExpression] = {}
        ch = len(pre_exprs)
        all_fields = list(pre_fields)
        for part_chs, order_keys, fns, calls in specs:
            node = P.Window(node, part_chs, order_keys, fns)
            for fn, call in zip(fns, calls):
                in_t = (
                    None if fn.arg_channel is None
                    else pre_fields[fn.arg_channel].type
                    if fn.arg_channel < len(pre_fields)
                    else pre_exprs[fn.arg_channel].type
                )
                out_t = W.result_type(fn, in_t)
                win_subst[call] = ir.InputRef(ch, out_t)
                all_fields.append(Field(None, out_t))
                ch += 1

        new_plan = RelationPlan(node, all_fields)
        new_scope = Scope(pre_fields, scope.parent)
        return new_plan, new_scope, win_subst

    def _plan_aggregation_block(
        self,
        plan: RelationPlan,
        scope: Scope,
        group_irs: List[ir.RowExpression],
        select_items: List[N.SelectItem],
        having: Optional[N.Node],
        include_keys: bool = False,
        grouping_sets=None,
    ):
        """GROUP BY block: pre-project group keys + agg args, aggregate,
        post-project select expressions with agg calls substituted
        (reference: QueryPlanner.planGroupingOperations + Aggregation
        symbol mapping)."""
        tr = ExprTranslator(self, scope)

        aggs: List[N.FunctionCall] = []
        for item in select_items:
            aggs.extend(find_aggregates(item.expr))
        if having is not None:
            aggs.extend(find_aggregates(having))
        # dedupe structurally
        uniq_aggs: List[N.FunctionCall] = []
        for a in aggs:
            if a not in uniq_aggs:
                uniq_aggs.append(a)

        distinct_aggs = [a for a in uniq_aggs if a.distinct]
        plain_aggs = [a for a in uniq_aggs if not a.distinct]

        # global collect aggregates (array_agg/map_agg/approx_percentile
        # with no GROUP BY) reuse the grouped machinery via a synthetic
        # constant key — the [cap, K] collect state needs the grouped
        # kernels. Divergence: over an EMPTY input this yields zero rows
        # where the reference yields one NULL row.
        if not group_irs and any(
            _canon_agg(a.name) in AS.COLLECT_FNS for a in uniq_aggs
        ):
            group_irs = [ir.Constant(0, T.BIGINT)]

        # pre-projection: group keys then agg arguments
        pre_exprs: List[ir.RowExpression] = list(group_irs)
        agg_arg_ch: List[Optional[int]] = []
        agg_arg_ir: List[Optional[ir.RowExpression]] = []
        agg_extra_ch: List[tuple] = []
        agg_extra_ir: List[tuple] = []
        agg_params: List[tuple] = []

        def _arg_channel(e: ir.RowExpression) -> int:
            idx = None
            if e in pre_exprs:
                i0 = pre_exprs.index(e)
                # under GROUPING SETS an aggregate argument must NOT
                # alias a group-key channel: GroupId nulls absent keys
                # per replica and would null the aggregate's input too
                if grouping_sets is None or i0 >= len(group_irs):
                    idx = i0
            if idx is None:
                pre_exprs.append(e)
                idx = len(pre_exprs) - 1
            return idx

        for a in uniq_aggs:
            if a.is_star or not a.args:
                agg_arg_ch.append(None)
                agg_arg_ir.append(None)
                agg_extra_ch.append(())
                agg_extra_ir.append(())
                agg_params.append(())
                continue
            cname = _canon_agg(a.name)
            e = _decimal_safe(tr.translate(a.args[0]))
            if cname in AS.VARIANCE_FNS and e.type != T.DOUBLE:
                e = ir.cast(e, T.DOUBLE)
            agg_arg_ch.append(_arg_channel(e))
            agg_arg_ir.append(e)
            extras_c: List[int] = []
            extras_e: List[ir.RowExpression] = []
            prms: tuple = ()
            if cname == "map_agg":
                if len(a.args) != 2:
                    raise PlanningError("map_agg takes (key, value)")
                e2 = _decimal_safe(tr.translate(a.args[1]))
                extras_c.append(_arg_channel(e2))
                extras_e.append(e2)
            elif cname == "approx_percentile":
                if len(a.args) != 2:
                    raise PlanningError(
                        "approx_percentile takes (value, fraction)"
                    )
                pe = tr.translate(a.args[1])
                if not isinstance(pe, ir.Constant) or pe.value is None:
                    raise PlanningError(
                        "approx_percentile fraction must be a constant"
                    )
                frac = pe.value
                if isinstance(pe.type, T.DecimalType):
                    frac = frac / (10 ** pe.type.scale)
                prms = (float(frac),)
            agg_extra_ch.append(tuple(extras_c))
            agg_extra_ir.append(tuple(extras_e))
            agg_params.append(prms)
        pre_fields = [Field(None, e.type) for e in pre_exprs]
        pre = RelationPlan(P.Project(plan.node, tuple(pre_exprs)),
                           pre_fields)

        nkeys = len(group_irs)
        # GROUPING SETS: expand through GroupId and aggregate over
        # (keys..., gid) — absent keys are NULLed per replica, and the
        # gid keeps visibly-equal groups of different sets apart
        # (reference: plan/GroupIdNode lowering)
        gid_extra = 0
        if grouping_sets is not None:
            if distinct_aggs:
                raise PlanningError(
                    "DISTINCT aggregates with GROUPING SETS are not "
                    "supported yet"
                )
            gid_extra = 1
        d_channels = sorted({
            ch for a, ch in zip(uniq_aggs, agg_arg_ch) if a.distinct
        })
        if distinct_aggs and not plain_aggs and len(d_channels) == 1:
            # two-level: dedupe (keys + the one arg), then aggregate over
            # the dedup — exchange-friendly, stays fully sharded
            dedup_channels = tuple(range(len(pre_exprs)))
            dedup = P.Aggregation(
                pre.node, dedup_channels, (),
                capacity=_agg_capacity(pre.node, self.catalogs),
            )
            specs = []
            for a, ch, ec, pr in zip(uniq_aggs, agg_arg_ch,
                                     agg_extra_ch, agg_params):
                fn = "count" if a.name == "count" else _canon_agg(a.name)
                specs.append(P.AggSpec(fn, ch, extra_channels=ec,
                                       params=pr))
            agg_node = P.Aggregation(
                dedup, tuple(range(nkeys)), tuple(specs),
                capacity=_agg_capacity(dedup, self.catalogs),
            )
        elif distinct_aggs:
            # general case — mixed DISTINCT/plain or several distinct
            # argument columns: MarkDistinct appends a first-occurrence
            # mark per (group keys, arg) set, and each distinct aggregate
            # reads its input through its mask (reference:
            # plan/MarkDistinctNode + AggregationNode mask symbols)
            mark_sets = tuple(
                tuple(range(nkeys)) + (c,) for c in d_channels
            )
            mark_of = {
                c: len(pre_exprs) + i for i, c in enumerate(d_channels)
            }
            md = P.MarkDistinct(pre.node, mark_sets)
            specs = []
            for a, ch, ec, pr in zip(uniq_aggs, agg_arg_ch,
                                     agg_extra_ch, agg_params):
                fn = _canon_agg(a.name)
                if a.is_star or (fn == "count" and ch is None):
                    specs.append(P.AggSpec("count_star", None))
                elif a.distinct:
                    specs.append(P.AggSpec(fn, ch, mask=mark_of[ch],
                                           extra_channels=ec, params=pr))
                else:
                    specs.append(P.AggSpec(fn, ch, extra_channels=ec,
                                           params=pr))
            agg_node = P.Aggregation(
                md, tuple(range(nkeys)), tuple(specs),
                capacity=_agg_capacity(pre.node, self.catalogs),
            )
        else:
            specs = []
            for a, ch, ec, pr in zip(uniq_aggs, agg_arg_ch,
                                     agg_extra_ch, agg_params):
                fn = _canon_agg(a.name)
                if a.is_star or (fn == "count" and ch is None):
                    specs.append(P.AggSpec("count_star", None))
                else:
                    specs.append(P.AggSpec(fn, ch, extra_channels=ec,
                                           params=pr))
            src_node = pre.node
            group_channels = tuple(range(nkeys))
            if grouping_sets is not None:
                masks = tuple(
                    tuple(i in s for i in range(nkeys))
                    for s in grouping_sets
                )
                src_node = P.GroupId(pre.node, tuple(range(nkeys)),
                                     masks)
                # gid channel appended after every pre-projection column
                group_channels = group_channels + (len(pre_exprs),)
            agg_node = P.Aggregation(
                src_node, group_channels, tuple(specs),
                capacity=_agg_capacity(src_node, self.catalogs),
            )

        # aggregate output fields: keys (then gid) then one per agg
        out_fields: List[Field] = []
        for i, g in enumerate(group_irs):
            nm = None
            out_fields.append(Field(nm, g.type))
        for _ in range(gid_extra):
            out_fields.append(Field(None, T.BIGINT))
        for a, e, ee in zip(uniq_aggs, agg_arg_ir, agg_extra_ir):
            if a.is_star or e is None:
                out_t = T.BIGINT
            elif a.distinct and a.name == "count":
                out_t = T.BIGINT
            else:
                out_t = AS.result_type(
                    _canon_agg(a.name), e.type,
                    tuple(x.type for x in ee),
                )
            out_fields.append(Field(None, out_t))
        agg_plan = RelationPlan(agg_node, out_fields)

        # substitution: agg AST -> channel; group ir -> channel
        subst: Dict[object, ir.RowExpression] = {}
        for i, a in enumerate(uniq_aggs):
            ref = ir.InputRef(
                nkeys + gid_extra + i,
                out_fields[nkeys + gid_extra + i].type,
            )
            subst[a] = ref
        group_map = {e: i for i, e in enumerate(group_irs)}

        agg_scope = Scope(agg_plan.fields)
        post_tr = ExprTranslator(
            self, scope, agg_subst=subst, group_subst=group_map,
            agg_fields=agg_plan.fields,
        )

        node = agg_plan.node
        if having is not None:
            h = post_tr.translate(having, root=False)
            node = P.Filter(node, h)

        exprs: List[ir.RowExpression] = []
        names: List[str] = []
        fields: List[Field] = []
        if include_keys:
            for i, g in enumerate(group_irs):
                exprs.append(ir.InputRef(i, g.type))
                names.append(None)
                fields.append(Field(None, g.type))
        for item in select_items:
            e = post_tr.translate(item.expr, root=True)
            nm = item.alias or (
                item.expr.name if isinstance(item.expr, N.Identifier)
                else None
            )
            exprs.append(e)
            names.append(nm)
            fields.append(Field(nm, e.type))
        out = RelationPlan(P.Project(node, tuple(exprs)), fields)
        return out, names

    def _order_keys(self, order_by, plan: RelationPlan):
        keys = []
        for o in order_by:
            ch = None
            if isinstance(o.expr, N.Identifier):
                for i, f in enumerate(plan.fields):
                    if f.name == o.expr.name:
                        ch = i
                        break
            elif isinstance(o.expr, N.Literal) and o.expr.kind == "long":
                ch = _ordinal(o.expr.value, len(plan.fields), "ORDER BY")
            if ch is None:
                raise PlanningError(
                    f"ORDER BY expression must reference an output column: "
                    f"{o.expr}"
                )
            keys.append(
                SortKey(ch, ascending=o.ascending, nulls_first=o.nulls_first)
            )
        return tuple(keys)

    # --------------------------------------------------- scalar subqueries
    def execute_scalar(self, q: N.Query) -> ir.Constant:
        """Eagerly run an uncorrelated scalar subquery and inline the value
        (reference: the engine keeps these as plan nodes; eager execution is
        our simplification — the value is a compile-time constant for every
        downstream jit)."""
        if self.scalar_executor is None:
            raise PlanningError(
                "scalar subqueries need an execution context"
            )
        if q in self.scalar_cache:
            return self.scalar_cache[q]
        sub = self._plan_uncorrelated_probe(q)
        if sub.channels != 1:
            raise PlanningError("scalar subquery must produce one column")
        rows = self.scalar_executor(sub.node)
        if len(rows) > 1:
            raise PlanningError("scalar subquery produced multiple rows")
        t = sub.fields[0].type
        value = rows[0][0] if rows else None
        if isinstance(t, T.DecimalType) and not t.is_short:
            if value is not None and abs(int(value)) < 2**62:
                out = ir.Constant(int(value), T.DecimalType(18, t.scale))
            else:
                out = ir.Constant(
                    None if value is None else float(value) / 10**t.scale,
                    T.DOUBLE,
                )
        else:
            out = ir.Constant(value, t)
        self.scalar_cache[q] = out
        return out


def _ordinal(value: int, n: int, where: str) -> int:
    if not 1 <= value <= n:
        raise PlanningError(
            f"{where} ordinal {value} out of range (1..{n})"
        )
    return value - 1


def _query_to_spec(q: N.Query) -> N.QuerySpec:
    if (
        q.withs or q.order_by or q.limit is not None
        or not isinstance(q.body, N.QuerySpec)
    ):
        raise PlanningError("unsupported subquery shape")
    return q.body


def _and_ir(exprs: List[ir.RowExpression]) -> ir.RowExpression:
    if len(exprs) == 1:
        return exprs[0]
    return ir.and_(*exprs)


def _decimal_safe(e: ir.RowExpression) -> ir.RowExpression:
    """Cast long-decimal refs to double before further arithmetic (module
    docstring divergence note)."""
    if isinstance(e.type, T.DecimalType) and not e.type.is_short:
        return ir.cast(e, T.DOUBLE)
    return e


# ------------------------------------------------------------- translator


class ExprTranslator:
    """AST expression -> typed ir.RowExpression over scope channels
    (reference: sql/relational/SqlToRowExpressionTranslator after
    ExpressionAnalyzer typing)."""

    def __init__(
        self,
        planner: Planner,
        scope: Scope,
        agg_subst: Optional[Dict] = None,
        group_subst: Optional[Dict] = None,
        agg_fields: Optional[List[Field]] = None,
    ):
        self.planner = planner
        self.scope = scope
        self.agg_subst = agg_subst or {}
        self.group_subst = group_subst or {}
        self.agg_fields = agg_fields
        # innermost-first stack of lambda parameter scopes
        # ({name: ir.ParamRef}); see _tr_higher_order
        self._lambda_scopes: List[dict] = []

    def translate(self, e: N.Node, root: bool = False) -> ir.RowExpression:
        out = self._tr(e, root)
        return out

    def _sub(self, e: N.Node) -> Optional[ir.RowExpression]:
        if self.agg_subst and e in self.agg_subst:
            ref = self.agg_subst[e]
            return ref
        return None

    def _tr(self, e: N.Node, root: bool = False) -> ir.RowExpression:
        sub = self._sub(e)
        if sub is not None:
            return sub if root else _decimal_safe(sub)
        if self.group_subst:
            # group expression appearing verbatim in select/having
            probe = self._group_probe(e)
            if probe is not None:
                return probe

        if isinstance(e, N.Identifier):
            if self._lambda_scopes and len(e.parts) == 1:
                # innermost frame ONLY: ParamRef indices are frame-
                # local, so an outer lambda's parameter inside a nested
                # lambda would silently alias the inner page's params —
                # raise (with the capture error below) instead
                ref = self._lambda_scopes[-1].get(e.parts[0])
                if ref is not None:
                    return ref
            if self._lambda_scopes:
                raise PlanningError(
                    f"lambda bodies cannot capture columns or outer "
                    f"lambda parameters ({'.'.join(e.parts)}); only "
                    f"this lambda's parameters and constants are "
                    f"allowed"
                )
            lvl, ch, f = self.scope.resolve(e)
            if lvl == 0:
                if self.group_subst is not None and self.agg_fields:
                    # inside an aggregation block a bare column must be a
                    # group key (checked via group_subst probe above)
                    raise PlanningError(
                        f"column {e.name!r} is neither grouped nor "
                        f"aggregated"
                    )
                return ir.InputRef(ch, f.type)
            return OuterRef(ch, f.type)
        if isinstance(e, N.Literal):
            return _literal(e)
        if isinstance(e, N.ArrayLiteral):
            items = [self._tr(i) for i in e.items]
            vals = []
            elem_t: T.SqlType = T.UNKNOWN
            for it in items:
                if not isinstance(it, ir.Constant):
                    raise PlanningError(
                        "ARRAY[...] elements must be constants"
                    )
                vals.append(it.value)
                if not isinstance(it.type, T.UnknownType):
                    ct = (T.common_super_type(elem_t, it.type)
                          if not isinstance(elem_t, T.UnknownType)
                          else it.type)
                    if ct is None:
                        raise PlanningError(
                            f"ARRAY[] elements have incompatible types: "
                            f"{elem_t} vs {it.type}"
                        )
                    elem_t = ct
            return ir.Constant(tuple(vals), T.ArrayType(elem_t))
        if isinstance(e, N.UnaryOp):
            if e.op == "not":
                return ir.not_(self._tr(e.operand))
            v = self._tr(e.operand)
            if e.op == "-":
                if isinstance(v, ir.Constant) and v.value is not None:
                    return ir.Constant(-v.value, v.type)
                return ir.call("negate", v)
            return v
        if isinstance(e, N.BinaryOp):
            if e.op == "and":
                return ir.and_(self._tr(e.left), self._tr(e.right))
            if e.op == "or":
                return ir.or_(self._tr(e.left), self._tr(e.right))
            if e.op == "||":
                return ir.call("concat", self._tr(e.left), self._tr(e.right))
            return ir.call(_BINOP_FN[e.op], self._tr(e.left),
                           self._tr(e.right))
        if isinstance(e, N.Between):
            b = ir.between(self._tr(e.value), self._tr(e.low),
                           self._tr(e.high))
            return ir.not_(b) if e.negated else b
        if isinstance(e, N.InList):
            x = ir.in_(self._tr(e.value), *[self._tr(i) for i in e.items])
            return ir.not_(x) if e.negated else x
        if isinstance(e, N.Like):
            args = [self._tr(e.value), self._tr(e.pattern)]
            if e.escape is not None:
                args.append(self._tr(e.escape))
            x = ir.call("like", *args)
            return ir.not_(x) if e.negated else x
        if isinstance(e, N.IsNull):
            x = ir.is_null(self._tr(e.value))
            return ir.not_(x) if e.negated else x
        if isinstance(e, N.Case):
            return self._tr_case(e)
        if isinstance(e, N.Cast):
            to = T.parse_type(e.type_name)
            if e.safe:
                return ir.Call("try_cast", (self._tr(e.value),), to)
            return ir.cast(self._tr(e.value), to)
        if isinstance(e, N.Extract):
            return ir.call(e.field.lower(), self._tr(e.value))
        if isinstance(e, N.FunctionCall):
            if _is_agg_name(e.name) or e.is_star:
                raise PlanningError(
                    f"aggregate {e.name} in invalid context"
                )
            # special forms spelled as function calls
            if e.name == "coalesce":
                return ir.coalesce(*[self._tr(a) for a in e.args])
            if e.name == "nullif":
                # `a` appears twice in the IR; XLA CSEs the identical
                # subgraphs under jit, so it is not evaluated twice on device
                a, b = (self._tr(x) for x in e.args)
                return ir.if_(
                    ir.call("eq", a, b), ir.Constant(None, a.type), a
                )
            if e.name == "if":
                args = [self._tr(a) for a in e.args]
                if len(args) == 2:
                    args.append(ir.Constant(None, args[1].type))
                return ir.if_(*args)
            if any(isinstance(a, N.Lambda) for a in e.args):
                return self._tr_higher_order(e)
            return ir.call(e.name, *[self._tr(a) for a in e.args])
        if isinstance(e, N.ScalarSubquery):
            return self.planner.execute_scalar(e.query)
        if isinstance(e, N.Parameter):
            raise PlanningError(
                f"parameter ?{e.index + 1} is not bound — run via "
                f"EXECUTE <name> USING <values>"
            )
        raise PlanningError(f"unsupported expression: {type(e).__name__}")

    def _tr_higher_order(self, e: N.FunctionCall) -> ir.RowExpression:
        """Higher-order function call: non-lambda args translate
        normally; lambda parameters bind to the collection's element
        type(s) (reference: ExpressionAnalyzer's lambda type
        inference against the function signature)."""
        first = self._tr(e.args[0])
        t0 = first.type
        if isinstance(t0, T.ArrayType):
            param_types = [t0.element]
        elif isinstance(t0, T.MapType):
            param_types = [t0.key, t0.value]
        else:
            raise PlanningError(
                f"{e.name}: first argument must be an array or map, "
                f"got {t0}"
            )
        out_args: List[ir.RowExpression] = [first]
        for pos, a in enumerate(e.args[1:], start=1):
            if not isinstance(a, N.Lambda):
                out_args.append(self._tr(a))
                continue
            if e.name == "reduce":
                # combine is (state, element) -> state; the optional
                # output lambda is state -> result; the state type
                # comes from the (already translated) initial value
                state_t = (out_args[1].type if len(out_args) > 1
                           else T.UNKNOWN)
                want = ([state_t, param_types[0]] if pos == 2
                        else [state_t])
            elif (e.name == "transform_values"
                    and len(a.params) == 1):
                want = [param_types[1]]  # v -> ... binds the value
            elif e.name == "zip_with":
                # (x, y) -> ... binds both arrays' element types
                t1 = out_args[1].type if len(out_args) > 1 else T.UNKNOWN
                want = [
                    param_types[0],
                    t1.element if isinstance(t1, T.ArrayType)
                    else T.UNKNOWN,
                ]
            else:
                want = (param_types if len(a.params) == len(param_types)
                        else param_types[: len(a.params)])
            if len(a.params) != len(want):
                raise PlanningError(
                    f"{e.name}: lambda takes {len(a.params)} "
                    f"parameters, expected {len(want)}"
                )
            frame = {
                p: ir.ParamRef(i, t)
                for i, (p, t) in enumerate(zip(a.params, want))
            }
            self._lambda_scopes.append(frame)
            try:
                body = self._tr(a.body)
            finally:
                self._lambda_scopes.pop()
            out_args.append(
                ir.Lambda(len(a.params), body, body.type)
            )
        return ir.call(e.name, *out_args)

    def _group_probe(self, e: N.Node) -> Optional[ir.RowExpression]:
        """If e translates (in the pre-agg scope) to a group expression,
        return the key channel ref."""
        try:
            pre = ExprTranslator(self.planner, self.scope).translate(e)
        except PlanningError:
            return None
        if pre in self.group_subst:
            ch = self.group_subst[pre]
            return ir.InputRef(ch, pre.type)
        return None

    def _tr_case(self, e: N.Case) -> ir.RowExpression:
        args: List[ir.RowExpression] = []
        for when, then in e.whens:
            if e.operand is not None:
                cond = ir.call("eq", self._tr(e.operand), self._tr(when))
            else:
                cond = self._tr(when)
            args.append(cond)
            args.append(self._tr(then))
        thens = args[1::2]
        if e.default is not None:
            default = self._tr(e.default)
        else:
            default = ir.Constant(None, thens[0].type)
        return ir.switch(*args, default)


def _literal(e: N.Literal) -> ir.Constant:
    if e.kind == "long":
        return ir.Constant(e.value, T.BIGINT)
    if e.kind == "double":
        return ir.Constant(float(e.value), T.DOUBLE)
    if e.kind == "decimal":
        text = str(e.value)
        if "." in text:
            intpart, frac = text.split(".")
        else:
            intpart, frac = text, ""
        scale = len(frac)
        digits = (intpart + frac).lstrip("0") or "0"
        precision = max(len(digits), scale, 1)
        unscaled = int(intpart + frac) if (intpart + frac) else 0
        return ir.Constant(unscaled, T.DecimalType(precision, scale))
    if e.kind == "string":
        return ir.Constant(e.value, T.VARCHAR)
    if e.kind == "boolean":
        return ir.Constant(bool(e.value), T.BOOLEAN)
    if e.kind == "null":
        return ir.Constant(None, T.UNKNOWN)
    if e.kind == "date":
        d = datetime.date.fromisoformat(e.value)
        return ir.Constant((d - _EPOCH).days, T.DATE)
    if e.kind == "timestamp":
        dt = datetime.datetime.fromisoformat(e.value)
        micros = int(
            (dt - datetime.datetime(1970, 1, 1)).total_seconds() * 1e6
        )
        return ir.Constant(micros, T.TIMESTAMP)
    if e.kind == "interval":
        amount, unit = e.value
        unit = unit.rstrip("s")
        if unit == "day":
            return ir.Constant(amount * 86_400_000_000, T.INTERVAL_DAY_TIME)
        if unit == "hour":
            return ir.Constant(amount * 3_600_000_000, T.INTERVAL_DAY_TIME)
        if unit == "minute":
            return ir.Constant(amount * 60_000_000, T.INTERVAL_DAY_TIME)
        if unit == "second":
            return ir.Constant(amount * 1_000_000, T.INTERVAL_DAY_TIME)
        if unit == "week":
            return ir.Constant(amount * 7 * 86_400_000_000,
                               T.INTERVAL_DAY_TIME)
        if unit == "month":
            return ir.Constant(amount, T.INTERVAL_YEAR_MONTH)
        if unit == "year":
            return ir.Constant(amount * 12, T.INTERVAL_YEAR_MONTH)
        raise PlanningError(f"unsupported interval unit: {unit}")
    raise PlanningError(f"unsupported literal kind: {e.kind}")
