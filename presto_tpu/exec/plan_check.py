"""Pre-compile plan verifier: machine-check the engine's plan
invariants before anything traces or launches.

Reference: presto-main's sql/planner/sanity/PlanSanityChecker — a
validation pass over every finished plan (type consistency, symbol
resolution, exchange partitioning agreement) that runs in tests and
can be enabled in production, catching planner drift at plan time
instead of as a wrong answer three operators later. This engine's
rebuild discipline (PAPER.md §1) rests on invariants that were
enforced only by whichever test happened to trip:

  1. SCHEMA-CONSISTENT EDGES — every operator edge and inter-fragment
     exchange agrees on channel count and type family; expression
     channel references resolve inside their input's width; exchange
     partition symbols agree on both sides of a co-partitioned join.
  2. LADDER-QUANTIZED CAPACITIES — every buffer the executor will
     allocate (membudget.audit shares the executor's sizing verbatim)
     lands ON the shapes.py bucket ladder, UNDER the device fault line
     and the HBM governor's budget.
  3. CANONICAL JIT-KEY MATERIAL — plan content that feeds program
     cache keys is identity-free and order-free: no dicts (ordering),
     no unregistered objects (id()-dependent reprs), and re-keying the
     same plan twice is byte-identical (plan_serde roundtrip).
  4. DETERMINISTIC SPLIT ASSIGNMENT — every distributable task payload
     carries the (splitIndex, splitCount) fields the PR-5 retry path
     re-generates splits from; hash-mode payloads name real partition
     columns.

Wiring: `Executor._verify_plan` runs `verify` when the `plan_check`
session property enables it — "auto" is ON under pytest and
`bench.py --prewarm`, OFF on the hot serving path (the check is
pre-compile and costs ~1ms on bench-rung plans, but the serving path
pays nothing by default). `tools/plan_audit.py` sweeps every bench
rung and the TPC-H/TPC-DS test corpus through the same verifier and
exits nonzero on any violation.

Violations raise PlanCheckError with POINTED messages: which node,
which invariant, what to fix.
"""

from __future__ import annotations

import decimal
import math
from typing import List, Optional

from presto_tpu import types as T
from presto_tpu.exec import plan as P
from presto_tpu.exec import shapes as SH
from presto_tpu.expr.ir import InputRef, RowExpression


class PlanCheckError(ValueError):
    """One or more plan invariants failed pre-compile. `violations`
    holds every finding (the verifier does not stop at the first)."""

    def __init__(self, violations: List[str]):
        self.violations = list(violations)
        lines = "\n  - ".join(self.violations)
        super().__init__(
            f"plan verification failed ({len(self.violations)} "
            f"violation{'s' if len(self.violations) != 1 else ''}):"
            f"\n  - {lines}"
        )


_JOIN_TYPES = ("inner", "left", "right", "full", "semi", "anti")
_EXCHANGE_KINDS = ("repartition", "broadcast", "gather")
_AGG_STEPS = ("single", "partial", "final")

# canonical scalar atoms allowed in plan (= jit-key) material; dicts
# are rejected for ordering-dependence, arbitrary objects because
# their identity/repr leaks id() into keys
_CANONICAL_ATOMS = (type(None), bool, int, float, str, bytes,
                    decimal.Decimal)


def _family(t) -> str:
    """Coarse type family for edge-compatibility checks. Deliberately
    lenient — numeric/temporal types inter-operate throughout the
    engine (dates are day counts, decimals are unscaled ints), so only
    unambiguous mismatches (string vs numeric, boolean vs anything,
    mismatched complex types) flag."""
    if isinstance(t, T.UnknownType):
        return "any"
    if isinstance(t, T.BooleanType):
        return "boolean"
    if T.is_string(t):
        return "string"
    if isinstance(t, (T.VarbinaryType,)):
        return "varbinary"
    if isinstance(t, (T.ArrayType, T.MapType, T.RowType,
                      T.HllStateType, T.CollectStateType)):
        return type(t).__name__
    return "scalar"


def _compatible(a, b) -> bool:
    fa, fb = _family(a), _family(b)
    return fa == "any" or fb == "any" or fa == fb


def _label(node) -> str:
    return type(node).__name__


class _Verifier:
    def __init__(self, ex, plan, strict: bool = False):
        self.ex = ex
        self.plan = plan
        self.strict = strict
        self.violations: List[str] = []
        self._types = {}  # id(node) -> output types (memo)

    def add(self, node, msg: str) -> None:
        self.violations.append(f"{_label(node)}: {msg}")

    def types_of(self, node) -> Optional[list]:
        key = id(node)
        if key not in self._types:
            try:
                self._types[key] = self.ex.output_types(node)
            except Exception as e:  # noqa: BLE001 - converted to finding
                self._types[key] = None
                self.add(node, f"output schema is unresolvable: {e} "
                               f"(fix the plan edge or the catalog "
                               f"binding before execution)")
        return self._types[key]

    def width_of(self, node) -> Optional[int]:
        t = self.types_of(node)
        return None if t is None else len(t)

    # ------------------------------------------------- expression edges
    def check_expr(self, node, expr: RowExpression, src_types,
                   what: str) -> None:
        if isinstance(expr, InputRef):
            if not (0 <= expr.channel < len(src_types)):
                self.add(node, f"{what} references channel "
                               f"#{expr.channel} but the input has "
                               f"only {len(src_types)} channels "
                               f"(0..{len(src_types) - 1}) — a stale "
                               f"channel mapping from a rewrite")
            elif not _compatible(expr.type, src_types[expr.channel]):
                self.add(node, f"{what} reads channel #{expr.channel} "
                               f"as {expr.type} but the input edge "
                               f"carries {src_types[expr.channel]} — "
                               f"schema-inconsistent edge")
        for child in expr.children():
            self.check_expr(node, child, src_types, what)

    def _check_channels(self, node, channels, width, what) -> None:
        for ch in channels:
            if not (0 <= ch < width):
                self.add(node, f"{what} channel #{ch} out of range "
                               f"for a {width}-channel input "
                               f"(0..{width - 1})")

    # ----------------------------------------------------- node checks
    def check_node(self, node) -> None:
        if isinstance(node, P.TableScan):
            self._check_scan(node)
        elif isinstance(node, P.Values):
            for i, row in enumerate(node.rows):
                if len(row) != len(node.types):
                    self.add(node, f"row {i} has {len(row)} values "
                                   f"for {len(node.types)} declared "
                                   f"types")
        elif isinstance(node, P.Filter):
            src = self.types_of(node.source)
            if src is not None:
                self.check_expr(node, node.predicate, src, "predicate")
                if _family(node.predicate.type) not in ("boolean",
                                                        "any"):
                    self.add(node, f"predicate type is "
                                   f"{node.predicate.type}, expected "
                                   f"boolean")
        elif isinstance(node, P.Project):
            src = self.types_of(node.source)
            if src is not None:
                for i, e in enumerate(node.exprs):
                    self.check_expr(node, e, src, f"expr #{i}")
        elif isinstance(node, P.Aggregation):
            self._check_agg(node)
        elif isinstance(node, P.HashJoin):
            self._check_join(node)
        elif isinstance(node, P.Union):
            self._check_union(node)
        elif isinstance(node, P.Exchange):
            self._check_exchange(node)
        elif isinstance(node, P.Output):
            w = self.width_of(node.source)
            if w is not None and len(node.names) != w:
                self.add(node, f"{len(node.names)} output names for "
                               f"{w} channels")
        elif isinstance(node, P.RemoteSource):
            self._check_remote(node)
        elif isinstance(node, P.Sort):
            w = self.width_of(node.source)
            if w is not None:
                self._check_channels(
                    node, (k.channel for k in node.keys), w, "sort key")
        elif isinstance(node, P.TopN):
            w = self.width_of(node.source)
            if w is not None:
                self._check_channels(
                    node, (k.channel for k in node.keys), w, "sort key")
            if node.limit < 0:
                self.add(node, f"negative limit {node.limit}")
        elif isinstance(node, P.Limit):
            if node.count < 0 or node.offset < 0:
                self.add(node, f"negative count/offset "
                               f"({node.count}, {node.offset})")
        elif isinstance(node, P.Window):
            self._check_window(node)
        elif isinstance(node, P.MarkDistinct):
            w = self.width_of(node.source)
            if w is not None:
                for ks in node.mark_channel_sets:
                    self._check_channels(node, ks, w, "mark key")
        elif isinstance(node, P.GroupId):
            w = self.width_of(node.source)
            if w is not None:
                self._check_channels(node, node.key_channels, w,
                                     "grouping key")
            for i, m in enumerate(node.set_masks):
                if len(m) != len(node.key_channels):
                    self.add(node, f"set_masks[{i}] has {len(m)} "
                                   f"entries for "
                                   f"{len(node.key_channels)} keys")
        elif isinstance(node, P.Unnest):
            src = self.types_of(node.source)
            if src is not None:
                self._check_channels(node, (node.array_channel,),
                                     len(src), "array")

    def _check_scan(self, node: P.TableScan) -> None:
        conn = self.ex.catalogs.get(node.catalog)
        if conn is None:
            self.add(node, f"unknown catalog {node.catalog!r} "
                           f"(known: {sorted(self.ex.catalogs)})")
            return
        try:
            schema = conn.table_schema(node.table)
            known = set(schema.column_names())
        except Exception as e:  # noqa: BLE001 - converted to finding
            self.add(node, f"table {node.catalog}.{node.table} is "
                           f"unresolvable: {e}")
            return
        for c in node.columns:
            if c not in known:
                self.add(node, f"column {c!r} not in "
                               f"{node.catalog}.{node.table} "
                               f"(known: {sorted(known)})")
        for entry in node.constraint or ():
            if len(entry) != 3 or not isinstance(entry[0], str):
                self.add(node, f"malformed constraint entry "
                               f"{entry!r} (want (column, lo, hi))")
            elif entry[0] not in known:
                self.add(node, f"constraint column {entry[0]!r} not "
                               f"in {node.catalog}.{node.table}")

    def _check_agg(self, node: P.Aggregation) -> None:
        if node.step not in _AGG_STEPS:
            self.add(node, f"unknown step {node.step!r} "
                           f"(want one of {_AGG_STEPS})")
        if node.capacity < 0:
            self.add(node, f"negative group capacity {node.capacity}")
        src = self.types_of(node.source)
        if src is None:
            return
        self._check_channels(node, node.group_channels, len(src),
                             "group")
        if node.step == "final":
            # a final step's aggregate channels index the PARTIAL's
            # original input (recovered via origin), not the state
            # page — range checks happen on the partial fragment
            return
        for i, spec in enumerate(node.aggregates):
            chans = [c for c in (spec.channel, spec.mask) if c is not None]
            chans += list(spec.extra_channels)
            self._check_channels(node, chans, len(src),
                                 f"aggregate #{i} ({spec.function})")
            if spec.mask is not None and 0 <= spec.mask < len(src) \
                    and _family(src[spec.mask]) not in ("boolean",
                                                        "any"):
                self.add(node, f"aggregate #{i} mask channel "
                               f"#{spec.mask} is {src[spec.mask]}, "
                               f"expected boolean")

    def _check_join(self, node: P.HashJoin) -> None:
        if node.join_type not in _JOIN_TYPES:
            self.add(node, f"unknown join_type {node.join_type!r}")
        if len(node.left_keys) != len(node.right_keys):
            self.add(node, f"key arity mismatch: {len(node.left_keys)} "
                           f"left vs {len(node.right_keys)} right "
                           f"equi-join keys")
        if not node.left_keys:
            self.add(node, "equi-join with no keys (use CrossJoin for "
                           "a join without equality conditions)")
        lt, rt = self.types_of(node.left), self.types_of(node.right)
        if lt is not None:
            self._check_channels(node, node.left_keys, len(lt),
                                 "left key")
        if rt is not None:
            self._check_channels(node, node.right_keys, len(rt),
                                 "right key")
        if lt is not None and rt is not None:
            for lk, rk in zip(node.left_keys, node.right_keys):
                if 0 <= lk < len(lt) and 0 <= rk < len(rt) and \
                        not _compatible(lt[lk], rt[rk]):
                    self.add(node, f"key type mismatch: left #{lk} "
                                   f"({lt[lk]}) vs right #{rk} "
                                   f"({rt[rk]}) — rows can never "
                                   f"match across this edge")
        # inter-fragment exchange agreement: a co-partitioned join's
        # repartition exchanges must hash on exactly the join keys on
        # BOTH sides, or matching rows land on different shards
        left_ex = node.left if isinstance(node.left, P.Exchange) else None
        right_ex = (node.right if isinstance(node.right, P.Exchange)
                    else None)
        if left_ex is not None and right_ex is not None and \
                left_ex.kind == "repartition" and \
                right_ex.kind == "repartition":
            if tuple(left_ex.keys) != tuple(node.left_keys) or \
                    tuple(right_ex.keys) != tuple(node.right_keys):
                self.add(node, f"exchange partitioning disagrees with "
                               f"the join keys: left repartitions on "
                               f"{tuple(left_ex.keys)} vs join keys "
                               f"{tuple(node.left_keys)}, right on "
                               f"{tuple(right_ex.keys)} vs "
                               f"{tuple(node.right_keys)} — "
                               f"co-partitioned rows would not "
                               f"co-locate")

    def _check_union(self, node: P.Union) -> None:
        if not node.sources:
            self.add(node, "union of zero sources")
            return
        first = self.types_of(node.sources[0])
        if first is None:
            return
        for i, s in enumerate(node.sources[1:], 1):
            ts = self.types_of(s)
            if ts is None:
                continue
            if len(ts) != len(first):
                self.add(node, f"source #{i} emits {len(ts)} channels "
                               f"vs source #0's {len(first)}")
                continue
            for ch, (a, b) in enumerate(zip(first, ts)):
                if not _compatible(a, b):
                    self.add(node, f"source #{i} channel #{ch} is "
                                   f"{b}, source #0 carries {a} — "
                                   f"union branches disagree")

    def _check_exchange(self, node: P.Exchange) -> None:
        if node.kind not in _EXCHANGE_KINDS:
            self.add(node, f"unknown kind {node.kind!r} "
                           f"(want one of {_EXCHANGE_KINDS})")
        w = self.width_of(node.source)
        if node.kind == "repartition":
            if not node.keys:
                self.add(node, "repartition exchange with no "
                               "partition keys")
            elif w is not None:
                self._check_channels(node, node.keys, w, "partition")
        elif node.keys:
            self.add(node, f"{node.kind} exchange carries partition "
                           f"keys {tuple(node.keys)} — only "
                           f"repartition partitions by key")

    def _check_remote(self, node: P.RemoteSource) -> None:
        if not node.types:
            self.add(node, "no declared channel types for the "
                           "fragment edge")
        if node.origin is not None:
            ot = self.types_of(node.origin)
            if ot is None:
                return
            if len(ot) != len(node.types):
                self.add(node, f"declares {len(node.types)} channels "
                               f"but the remote fragment emits "
                               f"{len(ot)} — schema-inconsistent "
                               f"fragment edge")
            else:
                for ch, (a, b) in enumerate(zip(node.types, ot)):
                    if not _compatible(a, b):
                        self.add(node, f"channel #{ch} declared {a} "
                                       f"but the remote fragment "
                                       f"emits {b}")

    def _check_window(self, node: P.Window) -> None:
        src = self.types_of(node.source)
        if src is None:
            return
        self._check_channels(node, node.partition_channels, len(src),
                             "partition")
        self._check_channels(node, (k.channel for k in node.order_keys),
                             len(src), "order key")
        for i, fn in enumerate(node.functions):
            ch = getattr(fn, "arg_channel", None)
            if ch is not None:
                self._check_channels(node, (ch,), len(src),
                                     f"window fn #{i} arg")

    # -------------------------------------------- capacity / ladder
    def check_capacities(self) -> None:
        """Every buffer the executor WILL allocate (the membudget
        audit shares the executor's sizing verbatim) must sit ON the
        shapes.py ladder and under the device fault line + governor
        budget."""
        from presto_tpu.exec import membudget as MB

        try:
            report = MB.audit(self.ex, self.plan)
        except Exception as e:  # noqa: BLE001 - converted to finding
            self.violations.append(
                f"membudget audit failed: {e} (the plan cannot be "
                f"sized statically — fix the schema findings first)")
            return
        check_buffers(report, self.violations, strict=self.strict)

    # --------------------------------------------- jit-key canonical
    def check_canonical_keys(self) -> None:
        check_canonical_key_material(self.plan, self.violations)

    # ---------------------------------------------------------- run
    def run(self) -> None:
        seen = set()

        def walk(n):
            if id(n) in seen:  # shared subtrees verify once
                return
            seen.add(id(n))
            self.check_node(n)
            if isinstance(n, P.RemoteSource) and n.origin is not None:
                walk(n.origin)
            for c in n.children():
                walk(c)

        walk(self.plan)
        # schema findings first: capacity/key passes consume
        # output_types and serde, which presuppose resolvable edges
        if not self.violations:
            self.check_capacities()
            self.check_canonical_keys()


# The governed sizing paths keep hard floors (the agg fold cap floors
# at 8192 slots, ladder buckets at LADDER_MIN) that a test-forced
# UNREALISTICALLY tiny fault line can sit below; the verifier flags
# only buffers past both the governed line and the engine's own floor
# (the real line, shapes.DEVICE_FAULT_ROWS, is 512x this floor).
_FAULT_LINE_FLOOR = 1 << 14


def check_buffers(report, violations: List[str],
                  strict: bool = False) -> None:
    """Ladder/fault-line/budget checks over one membudget AuditReport
    (factored out so the mutation suite can drive it directly).

    strict=False (the per-query auto gate) exempts blocking
    whole-input merges (sort/window/markdistinct — '... merge'
    labels): they have NO chunked rewrite yet, the audit deliberately
    over-estimates them, and a test-forced tiny budget/fault line must
    not fail a query the engine executes correctly. strict=True (the
    plan_audit CLI and bench --prewarm, which run against REAL
    budgets) enforces every buffer."""
    for b in report.buffers:
        if b.rows != SH.bucket(b.rows):
            violations.append(
                f"buffer '{b.label}' capacity {b.rows} is OFF the "
                f"shapes.py bucket ladder (nearest rungs "
                f"{SH.bucket(b.rows) >> 1}/{SH.bucket(b.rows)}) — a "
                f"sizing path bypassed SH.bucket and will mint a "
                f"fresh program shape")
    no_rewrite = (lambda b: not strict and b.label.endswith(" merge"))
    for b in report.over_fault_line():
        if no_rewrite(b):
            continue
        if b.rows <= max(report.fault_rows or 0, _FAULT_LINE_FLOOR):
            continue
        violations.append(
            f"buffer '{b.label}' plans {b.rows} rows, past the "
            f"governed device fault line ({report.fault_rows} rows) "
            f"— the membudget governor must chunk this pipeline "
            f"(grace passes / position chunking / generation "
            f"chunking) before launch")
    for b in report.over_budget():
        if no_rewrite(b):
            continue
        violations.append(
            f"buffer '{b.label}' plans {b.bytes} bytes, past the "
            f"device-memory budget ({report.budget} bytes) — the "
            f"governed sizing paths should have clamped this buffer "
            f"to its budget share")


def check_canonical_key_material(plan, violations: List[str]) -> None:
    """Jit-cache keys are built from plan content (exec/shapes.py
    canonicalization, PR 2): that content must be identity-free and
    order-free, and re-keying the same plan twice must be
    byte-identical."""
    from presto_tpu.dist import plan_serde

    bad = []

    def walk(x, path):
        if isinstance(x, _CANONICAL_ATOMS):
            if isinstance(x, float) and not math.isfinite(x):
                return  # serde tags non-finite floats canonically
            return
        if isinstance(x, tuple):
            for i, v in enumerate(x):
                walk(v, f"{path}[{i}]")
            return
        if isinstance(x, dict):
            bad.append(f"{path}: dict (iteration-order-dependent — "
                       f"use a sorted tuple of pairs)")
            return
        if isinstance(x, (list, set, frozenset, bytearray)):
            bad.append(f"{path}: {type(x).__name__} (mutable/"
                       f"unordered — use a tuple)")
            return
        import dataclasses as _dc

        if _dc.is_dataclass(x) and not isinstance(x, type):
            for f in _dc.fields(x):
                walk(getattr(x, f.name), f"{path}.{f.name}")
            return
        bad.append(f"{path}: {type(x).__name__} object (its repr/"
                   f"hash depends on object identity — id() leaks "
                   f"into the program cache key)")

    walk(plan, _label(plan))
    for b in bad[:8]:
        violations.append(f"non-canonical jit-key material at {b}")
    if bad:
        return
    try:
        b1 = plan_serde.dumps(plan)
        b2 = plan_serde.dumps(plan_serde.loads(b1))
    except Exception as e:  # noqa: BLE001 - converted to finding
        violations.append(
            f"plan is not canonically serializable: {e} — program "
            f"cache keys derived from it cannot be stable")
        return
    if b1 != b2:
        violations.append(
            "re-keying the same plan produced DIFFERENT bytes across "
            "a serde roundtrip — some field depends on object "
            "identity or other non-canonical state")


def verify(ex, plan, strict: bool = False) -> None:
    """Verify one physical plan against an executor's catalogs and
    sizing knobs. Raises PlanCheckError listing EVERY violation with a
    pointed message; returns None on a clean plan. strict=True
    additionally enforces budget/fault-line bounds on blocking merges
    (see check_buffers) — the plan_audit/prewarm gate."""
    v = _Verifier(ex, plan, strict=strict)
    v.run()
    if v.violations:
        raise PlanCheckError(v.violations)


# --------------------------------------------------- task payloads
_PAYLOAD_REQUIRED = ("taskId", "splitIndex", "splitCount")


def check_task_payload(payload: dict) -> None:
    """Verify a DCN task payload carries the deterministic split
    assignment the PR-5 retry path depends on: a re-dispatched task
    re-generates EXACTLY splitIndex/splitCount's share at the scan, so
    these fields (not worker identity) must define the split set.
    Stage-DAG payloads may instead (or additionally) carry `sources`
    — spooled-exchange input edges a replayed task re-reads — each
    naming concrete producer placements."""
    bad: List[str] = []
    for k in _PAYLOAD_REQUIRED:
        if payload.get(k) is None:
            bad.append(f"task payload missing {k!r} — a retried task "
                       f"could not re-generate its split share "
                       f"deterministically")
    if not bad:
        idx, cnt = int(payload["splitIndex"]), int(payload["splitCount"])
        if not (0 <= idx < cnt):
            bad.append(f"splitIndex {idx} outside [0, splitCount="
                       f"{cnt}) — the split share is undefined")
    sources = payload.get("sources") or {}
    for key, spec in sources.items():
        tasks = (spec or {}).get("tasks")
        if not tasks or not all(
            isinstance(t, dict) and t.get("uri") and t.get("taskId")
            for t in tasks
        ):
            bad.append(f"source {key!r} lacks concrete producer "
                       f"placements (uri + taskId per task) — a "
                       f"replayed consumer could not re-read its "
                       f"spooled inputs")
        if int((spec or {}).get("partition", 0)) < 0:
            bad.append(f"source {key!r} names a negative spool "
                       f"partition")
        plist = (spec or {}).get("partitions")
        if plist is not None and (
            not plist or any(int(p) < 0 for p in plist)
        ):
            bad.append(f"source {key!r} carries an empty/negative "
                       f"adaptive partition list — a broadcast read "
                       f"must name every spooled partition")
    if payload.get("splitMode") == "hash":
        cols = payload.get("partitionColumns")
        if not cols or not isinstance(cols, dict) or not all(
            isinstance(k, str) and "." in k and isinstance(v, str)
            for k, v in cols.items()
        ):
            bad.append("hash splitMode without a catalog.table -> "
                       "column partitionColumns map — co-partitioned "
                       "scans cannot agree on the hash symbol")
    elif not payload.get("splitTable") and not sources:
        bad.append("round-robin task payload missing splitTable — "
                   "workers cannot derive disjoint split shares "
                   "(non-leaf stage-DAG tasks must carry sources "
                   "instead)")
    if payload.get("fragment") is None and not payload.get("sql"):
        bad.append("task payload carries neither a serialized "
                   "fragment nor legacy sql")
    if payload.get("outputPartitions") is not None:
        p = int(payload["outputPartitions"])
        if p < 1:
            bad.append(f"outputPartitions {p} < 1 — the spool would "
                       f"have no buffers")
        if p > 1 and not payload.get("outputKeys"):
            bad.append("repartitioned output (outputPartitions > 1) "
                       "without outputKeys — producers cannot agree "
                       "on the hash symbol")
    if bad:
        raise PlanCheckError(bad)


# ----------------------------------------------------- stage DAGs
def verify_dag(ex, dag, strict: bool = False) -> None:
    """Verify a fragmented stage DAG (dist/fragmenter.fragment_dag):
    every fragment root passes the full single-plan verifier (its
    RemoteSource leaves carry producer origins, so schema agreement is
    checked across EVERY exchange hop), plus the DAG-level invariants
    no single tree can express:

      - every RemoteSource edge resolves to a producer fragment whose
        declared output types it matches;
      - repartition output keys index real producer channels and are
        hash-partitionable across tasks (no dictionary-coded keys —
        codes are producer-local);
      - a join whose BOTH children arrive via repartition edges must
        be co-partitioned on exactly its join keys, or matching rows
        land in different partitions (the fragment-edge analog of the
        in-plan exchange-partitioning check) — unless an adaptive
        read override (dag.reads) drains one side broadcast-style,
        in which case co-location is no longer load-bearing;
      - a "passthrough" edge (the adaptive degrade of a repartition
        producer under a broadcast-flipped join) requires BOTH ends
        sharded: consumer task t reads producer task t's whole
        spool, which is a disjoint split only when task counts agree
        (the scheduler shards both over the same pool).
    """
    from presto_tpu.dist.fragmenter import stage_key

    read_kind = getattr(dag, "read_kind",
                        lambda c, p: dag.fragments[p].output_kind)
    violations: List[str] = []
    by_key = {stage_key(f.fid): f for f in dag.fragments}
    for frag in dag.fragments:
        try:
            verify(ex, frag.root, strict=strict)
        except PlanCheckError as e:
            violations.extend(
                f"stage {frag.fid}: {v}" for v in e.violations
            )
            continue
        if frag.output_kind == "passthrough":
            if not frag.sharded:
                violations.append(
                    f"stage {frag.fid}: passthrough output on an "
                    f"un-sharded fragment — a single producer task "
                    f"cannot feed every consumer task its own "
                    f"disjoint share")
            for c in dag.consumers(frag.fid):
                if not dag.fragments[c].sharded:
                    violations.append(
                        f"stage {frag.fid}: passthrough edge into "
                        f"un-sharded consumer stage {c} — task "
                        f"counts cannot agree")
        if frag.output_kind == "repartition":
            try:
                out = ex.output_types(frag.root)
            except Exception:  # noqa: BLE001 - verified above
                out = None
            if out is not None:
                for k in frag.output_keys:
                    if not (0 <= k < len(out)):
                        violations.append(
                            f"stage {frag.fid}: repartition key "
                            f"#{k} out of range for the fragment's "
                            f"{len(out)}-channel output")
                from presto_tpu.dist.fragmenter import (
                    _keys_repartitionable,
                )

                if all(0 <= k < len(out)
                       for k in frag.output_keys) and \
                        not _keys_repartitionable(out,
                                                  frag.output_keys):
                    violations.append(
                        f"stage {frag.fid}: repartition keys "
                        f"{tuple(frag.output_keys)} include a "
                        f"dictionary-coded channel — codes are "
                        f"producer-local, rows would not co-locate")

    def check_edges(plan, where, consumer_fid):
        def walk(n):
            if isinstance(n, P.RemoteSource) and \
                    n.key.startswith("stage"):
                frag = by_key.get(n.key)
                if frag is None:
                    violations.append(
                        f"{where}: RemoteSource {n.key!r} names no "
                        f"fragment in this DAG")
                else:
                    try:
                        ot = tuple(ex.output_types(frag.root))
                    except Exception:  # noqa: BLE001 - above
                        ot = None
                    # family agreement per channel is the single-plan
                    # verifier's job (via origin); the DAG edge check
                    # pins the arity against the LIVE fragment table
                    if ot is not None and len(n.types) != len(ot):
                        violations.append(
                            f"{where}: RemoteSource {n.key!r} "
                            f"declares {len(n.types)} channels but "
                            f"stage {frag.fid} emits {len(ot)}")
                return
            if isinstance(n, P.HashJoin):
                lsrc = n.left if isinstance(
                    n.left, P.RemoteSource) else None
                rsrc = n.right if isinstance(
                    n.right, P.RemoteSource) else None
                lf = by_key.get(lsrc.key) if lsrc is not None else None
                rf = by_key.get(rsrc.key) if rsrc is not None else None
                if lf is not None and rf is not None and \
                        read_kind(consumer_fid, lf.fid) \
                        == "repartition" and \
                        read_kind(consumer_fid, rf.fid) \
                        == "repartition":
                    if tuple(lf.output_keys) != tuple(n.left_keys) or \
                            tuple(rf.output_keys) != tuple(
                                n.right_keys):
                        violations.append(
                            f"{where}: join consumes repartitioned "
                            f"stages {lf.fid}/{rf.fid} but their "
                            f"partition keys "
                            f"{tuple(lf.output_keys)}/"
                            f"{tuple(rf.output_keys)} disagree with "
                            f"the join keys {tuple(n.left_keys)}/"
                            f"{tuple(n.right_keys)} — co-partitioned "
                            f"rows would not co-locate")
            for c in n.children():
                walk(c)

        walk(plan)

    for frag in dag.fragments:
        check_edges(frag.root, f"stage {frag.fid}", frag.fid)
    check_edges(dag.root, "coordinator fragment", -1)
    if violations:
        raise PlanCheckError(violations)
