"""THE execution-counter registry.

Reference: presto-main OperatorStats/QueryStats — every runtime counter
the engine maintains is declared once and every surfacing layer
renders the same declared set (JMX beans enumerate the declared stats;
nothing is hand-listed per endpoint). Before this registry each
counter was wired by hand into EXPLAIN ANALYZE, /metrics,
system.metrics, and analyze_rung separately — and PR after PR the
wiring drifted (split_batch_fallbacks and the spill counters never
reached /metrics at all). Now:

  - QUERY_COUNTERS declares every integer counter the Executor (and
    the DCN coordinator, via mirrored attributes) maintains;
  - Executor.execute_with_stats builds its EXPLAIN ANALYZE counter
    dict FROM the registry (plus the few computed entries listed in
    COMPUTED_COUNTERS);
  - the HTTP server's /metrics exposition and system.metrics table
    iterate the registry;
  - tools/analyze_rung.py prints every key of the stats dict, so
    registry membership IS analyze_rung coverage;
  - tools/lint's `counters` rule fails the build when a `self.x += 1`
    counter in exec/ or dist/ is missing from the registry.

Adding a counter = initialize it to 0 in Executor.__init__, increment
it, and add one row here; every surface picks it up.
"""

from __future__ import annotations

from typing import Dict

# attr name on Executor -> (prometheus kind, help text).
# "counter" = monotonically increasing over the executor's lifetime or
# per query; "gauge" = per-attempt/per-query level.
QUERY_COUNTERS: Dict[str, tuple] = {
    "gathers_deferred": (
        "gauge", "per-page column gathers skipped at join-output time "
        "(late materialization; per-attempt)"),
    "gathers_materialized": (
        "gauge", "per-page column value gathers actually performed "
        "(late-materialization lift + chain-boundary finish)"),
    "fused_partial_aggs": (
        "gauge", "scan→filter→project→partial-agg chains compiled to "
        "one XLA program per split this attempt"),
    "program_launches": (
        "gauge", "fused-scan program launches this attempt "
        "(split-batched execution)"),
    "splits_scanned": (
        "gauge", "real (unpadded) splits covered by this attempt's "
        "fused-scan launches — splits_per_launch is the ratio"),
    "split_batch_fallbacks": (
        "counter", "streams that fell back to the per-split loop "
        "because the chain did not trace under vmap/scan"),
    "generated_joins_used": (
        "counter", "build-free generated joins taken (lifetime; "
        "EXPLAIN ANALYZE reports the per-query delta)"),
    "pallas_joins_used": (
        "counter", "Pallas join kernel engagements (lifetime; EXPLAIN "
        "ANALYZE reports the per-query delta)"),
    "pallas_kernels_used": (
        "counter", "Pallas kernel engagements of ANY kind — join "
        "probes, segmented-reduction aggregations, partition-id "
        "exchange hashing (lifetime; the device-native kernel tier's "
        "overall engagement gauge)"),
    "ici_exchanges": (
        "counter", "repartition exchanges lowered to an in-program "
        "lax.all_to_all over the co-resident mesh instead of the "
        "spool/HTTP plane (dist/scheduler.py mesh-exchange plane; "
        "coordinator lifetime)"),
    "ici_bytes": (
        "counter", "bytes routed through mesh all_to_all exchange "
        "programs (send-buffer footprint of the settled attempt — "
        "interconnect traffic, never a host crossing; coordinator "
        "lifetime)"),
    "mesh_exchange_fallbacks": (
        "counter", "mesh-lowered exchanges that fell back LOUDLY to "
        "the authoritative spool plane (trace failure or unsettled "
        "overflow ladder) — counted, never a silent wrong answer"),
    "programs_compiled": (
        "gauge", "real XLA backend compiles attributed to this query "
        "(a persistent-cache hit counts as program_cache_hits)"),
    "program_cache_hits": (
        "gauge", "persistent compile-cache hits attributed to this "
        "query"),
    "spill_partitions_used": (
        "gauge", "grace-partition passes taken by joins/aggregations "
        "this query (spill_threshold_bytes / governed sizing)"),
    "host_spill_pages": (
        "gauge", "intermediate pages staged to host RAM this query "
        "(PageStore host tier)"),
    "disk_spill_pages": (
        "gauge", "intermediate pages written to disk spill files this "
        "query (PageStore disk tier)"),
    "skew_chunks_used": (
        "gauge", "hot grace-join partitions rebalanced by position "
        "chunking on boosted retries"),
    "memory_chunked_pipelines": (
        "gauge", "pipelines the HBM governor rewrote into "
        "chunked/streaming form this attempt (exec/membudget.py)"),
    "device_oom_retries": (
        "gauge", "device-OOM re-entries this query, each under a "
        "halved device-memory budget"),
    "task_retries": (
        "counter", "DCN fragments re-dispatched to a surviving worker "
        "(coordinator lifetime)"),
    "workers_excluded": (
        "counter", "DCN nodes dropped from the dispatch pool after a "
        "mid-query failure (coordinator lifetime)"),
    "release_skips": (
        "counter", "worker page-buffer DELETE releases skipped because "
        "the worker was unreachable (dead-worker cleanup, counted not "
        "swallowed; mirrored from the DCN coordinator)"),
    "stages_scheduled": (
        "counter", "stage-DAG fragments dispatched as worker task "
        "waves by the general scheduler (dist/scheduler.py; "
        "coordinator lifetime)"),
    "spooled_exchange_pages": (
        "counter", "pages published into worker-side spooled-exchange "
        "partitions (PageStore host/disk tiers on the producing "
        "worker; coordinator lifetime)"),
    "nonleaf_replays": (
        "counter", "lost NON-LEAF stage-DAG tasks re-dispatched to "
        "replay from spooled upstream pages instead of failing the "
        "query (coordinator lifetime)"),
    "speculative_tasks_won": (
        "counter", "straggler speculation races where the "
        "re-dispatched copy finished first and became the task's "
        "placement"),
    "speculative_tasks_lost": (
        "counter", "straggler speculation races the original "
        "placement won (the speculated copy was cancelled)"),
    "capacity_boost_retries": (
        "gauge", "overflow-ladder boosted re-entries this query "
        "(0 on a profile-seeded repeat run — the observed-stats "
        "profile contract, obs/profile.py)"),
    "profile_store_hits": (
        "gauge", "runs whose starting capacity bucket was seeded "
        "from a persisted observed-stats profile (obs/profile.py; "
        "per query)"),
    "result_cache_hits": (
        "counter", "result-cache hits: fragment page replays + full-"
        "statement row replays (presto_tpu/cache/; executor lifetime "
        "— /metrics and system.metrics overlay the process-shared "
        "store's totals)"),
    "result_cache_misses": (
        "counter", "result-cache lookups that executed for real (the "
        "entry is published when the attempt completes overflow-free)"),
    "result_cache_evictions": (
        "counter", "result-cache entries dropped by the byte-budget "
        "LRU or TTL aging (result_cache_bytes / result_cache_ttl_ms)"),
    "result_cache_invalidations": (
        "counter", "result-cache entries reclaimed by the write-path "
        "invalidation hook after DML/CTAS to their scanned tables "
        "(staleness itself is structural: snapshot_version rides in "
        "every key)"),
    "cache_warm_loads": (
        "counter", "persisted result-cache entries re-admitted at the "
        "warm-start pass (cache/persist.py manifest load): snapshot "
        "tokens re-validated against live connectors, pages decoded "
        "from the wire-serde payload files"),
    "cache_manifest_drops": (
        "counter", "persisted result-cache entries dropped LOUDLY at "
        "warm load: snapshot token moved, payload file missing or "
        "corrupt, manifest truncated, or wire-serde fingerprint "
        "mismatch — never served, never a crash"),
    "checkpoints_written": (
        "counter", "durable coordinator checkpoint records published "
        "to the query journal (dist/checkpoint.py): admission, stage "
        "barriers, root registration, drain progress, client-token "
        "advances (coordinator lifetime)"),
    "coordinator_reattaches": (
        "counter", "journaled queries a RESTARTED coordinator "
        "recovered — final-stage suppliers re-registered from "
        "persisted placements (spool resume) or the statement re-run "
        "from the journal (coordinator lifetime)"),
    "reattach_redispatches": (
        "counter", "dead final-stage placements re-dispatched from "
        "persisted payloads during coordinator re-attach (the lost "
        "suffix, through the normal replay ladder; coordinator "
        "lifetime)"),
    "checkpoint_drops": (
        "counter", "checkpoint records dropped LOUDLY: journal "
        "generations unreadable at boot (version/fingerprint skew, "
        "torn appends, partial compaction) or barrier writes that "
        "failed to serialize — recovery degrades to the re-run rung, "
        "never a crash, never stale state served"),
    "probe_deadline_skips": (
        "counter", "remote-cache probes skipped because the query's "
        "remaining query_max_run_time could not afford the probe "
        "wall (deadline-aware retry budget; the task dispatched "
        "normally instead)"),
    "cache_remote_hits": (
        "counter", "leaf tasks short-circuited by a FLEET member's "
        "fragment cache: the coordinator's pre-dispatch probe "
        "(dist/cacheprobe.py) found the fragment's pages on a worker "
        "and replayed them over the pooled spool-fetch plane instead "
        "of executing the task"),
    "cache_subsumed_hits": (
        "counter", "fragments served by CONTAINMENT rewrite "
        "(cache/rules.py): a cached sibling with a wider single-"
        "column range/IN filter replayed through this fragment's own "
        "predicate as a residual re-filter"),
    "h2d_bytes": (
        "gauge", "bytes staged host->device through the exec/xfer.py "
        "choke points this query (0 on a cache replay served from "
        "host pages — the ISSUE 12 zero-copy contract)"),
    "d2h_bytes": (
        "gauge", "bytes pulled device->host through the exec/xfer.py "
        "choke points this query (spill, exchange serialization, "
        "result decode)"),
    "h2d_transfers": (
        "gauge", "host->device crossings this query (exec/xfer.py; "
        "transfer_wall_s carries their summed wall as a computed "
        "entry)"),
    "d2h_transfers": (
        "gauge", "device->host crossings this query (exec/xfer.py)"),
    "buffers_donated": (
        "gauge", "donated-program invocations this attempt "
        "(fold/topn merge accumulators reusing their input's HBM in "
        "place via donate_argnums; buffer_donation_enabled)"),
    "exchange_wire_bytes": (
        "counter", "exchange-page bytes actually shipped on the wire "
        "by dist/serde.serialize_page (post-codec blob size; "
        "executor lifetime — exchange_raw_bytes / exchange_wire_bytes "
        "is the wire compression ratio)"),
    "exchange_raw_bytes": (
        "counter", "pre-codec array bytes behind the serialized "
        "exchange pages (what a raw wire would have shipped; "
        "executor lifetime)"),
    "exchange_fetch_reused_conns": (
        "counter", "shuffle-plane HTTP requests served on a reused "
        "keep-alive connection from dist/connpool.py instead of a "
        "fresh TCP connect (executor lifetime)"),
    "mesh_local_exchanges": (
        "counter", "exchanges that never left the device/process: "
        "spooled edges served Pages directly between same-process "
        "placements (dist/spool.local_source_pages — no HTTP, no "
        "serde) and DistExecutor collective exchanges compiled onto "
        "the mesh (all_to_all/all_gather; executor lifetime)"),
    "delta_pages_folded": (
        "counter", "delta partial-state pages folded into persisted "
        "materialized-view state by incremental refreshes "
        "(streaming/ivm.py — the O(new rows) refresh input; executor "
        "lifetime)"),
    "ivm_refreshes": (
        "counter", "incremental materialized-view refreshes completed "
        "(delta fold through the partial-agg kernels + finalize; "
        "streaming/ivm.py)"),
    "ivm_full_recomputes": (
        "counter", "view refreshes that fell back to a FULL recompute "
        "(non-IVM-safe plan shape or ivm_enabled=false) — the loud, "
        "counted degradation path, never a silent wrong answer"),
    "cursor_polls": (
        "counter", "tailing /v1/statement cursor polls served "
        "(stream_tail_enabled; each poll long-polls the append log "
        "and emits only delta-derived rows)"),
    "stream_appends_seen": (
        "counter", "append batches observed on append-only stream "
        "connectors: the runner's INSERT advance path plus tail "
        "polls that saw the log offset move"),
    "adaptive_replans": (
        "counter", "stage-boundary re-plans applied by the adaptive "
        "executor (presto_tpu/adaptive/): the not-yet-dispatched "
        "suffix of a stage DAG was re-optimized from exact spool "
        "stats and re-verified before dispatch (coordinator "
        "lifetime)"),
    "adaptive_dist_flips": (
        "counter", "join distributions flipped at runtime by the "
        "adaptive re-planner (partitioned -> broadcast reads of a "
        "small observed build, repartition producers degraded to "
        "passthrough) — the AddExchanges decision re-made on "
        "measured bytes"),
    "adaptive_capacity_seeds": (
        "counter", "downstream fragment capacities re-bucketed onto "
        "the shapes.py ladder from observed exchange cardinality "
        "(aggregation capacities, RemoteSource est_rows stamps) so "
        "first runs start at the settled bucket instead of climbing "
        "the boost ladder"),
    "adaptive_replan_rejected": (
        "counter", "adaptive re-plans DISCARDED because the mutated "
        "DAG failed plan_check.verify_dag (or the per-query "
        "adaptive_max_replans bound was hit) — the static plan runs "
        "instead, counted loudly, never a silent wrong answer"),
    "skew_preempted": (
        "counter", "grace-join passes that started in the skew-"
        "rebalanced position-chunking mode on their FIRST attempt "
        "because the adaptive re-planner saw a hot partition in the "
        "upstream spool histogram (vs discovering it via an overflow "
        "retry; worker counts mirror onto the coordinator)"),
    "trace_spans": (
        "gauge", "spans recorded into this query's lifecycle trace "
        "(obs/trace.py; pinned 0 when tracing is off)"),
    "listener_errors": (
        "counter", "EventListener exceptions swallowed by the "
        "events.dispatch choke point — counted here instead of lost "
        "silently (executor lifetime)"),
    "cross_query_batches": (
        "counter", "shared cross-query device steps dispatched by "
        "this executor as a gather-group LEADER "
        "(server/launch_batcher.py; executor lifetime — the leader's "
        "one launch covers every ganged query)"),
    "cross_query_batched_queries": (
        "counter", "launches this executor served FROM a shared "
        "cross-query batch instead of a solo program (leader and "
        "follower slots both count; executor lifetime)"),
    "batch_gather_wait_ms": (
        "counter", "milliseconds this executor's launches spent in "
        "the cross-query gather window (bounded by "
        "cross_query_batch_wait_ms per launch; executor lifetime)"),
    "queries_per_launch": (
        "gauge", "widest cross-query batch this executor rode (slots "
        "per shared launch; 0 = every launch ran solo)"),
}

# stats-dict entries that are COMPUTED in execute_with_stats rather
# than read off an executor attribute (the lint's counters rule knows
# not to look for `self.<name> +=` sites for these).
COMPUTED_COUNTERS = (
    "splits_per_launch",     # splits_scanned / program_launches
    "compile_wall_s",        # float wall, not an int counter
    "transfer_wall_s",       # float wall of metered crossings (xfer)
    "peak_device_bytes",     # high-water gauge (max, not +=)
    "deadline_ms_remaining",  # derived from query_deadline
)


def snapshot(ex) -> Dict[str, int]:
    """Registry-driven counter snapshot of one executor — the shared
    source for /metrics and system.metrics (missing attributes read 0
    so a bare Executor and a DCN coordinator render the same rows)."""
    return {name: int(getattr(ex, name, 0)) for name in QUERY_COUNTERS}
