"""THE device-fault classifier: one marker list, one predicate.

Reference: presto-main's StandardErrorCode taxonomy — every layer that
reacts to an error class consults the SAME classification (raw text
matching scattered per call site is how retry ladders silently drift).
Here the class is "device memory/allocation fault": the signal that
admits an execution into the OOM-degradation ladder (executor
execute()/stream_fragment() re-enter under a halved budget) and that
the DCN coordinator uses to recognize a worker-side device fault
quoted in an X-Task-Error payload. Both importers share this module so
the marker list cannot drift between the local and distributed paths
(ISSUE 6 satellite: the classifier was headed for copy-paste
duplication in dist/dcn.py).
"""

from __future__ import annotations

# Substrings that mark a device memory/allocation failure in XLA / TPU
# runtime error text (RESOURCE_EXHAUSTED is the canonical status; the
# allocator variants appear on CPU/older stacks).
DEVICE_FAULT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "Failed to allocate",
)


def text_matches(msg: str) -> bool:
    """Whether error TEXT carries a device-memory marker — the half of
    the classification the DCN coordinator can apply to a worker's
    quoted error string (no exception object crosses the wire)."""
    return any(m in msg for m in DEVICE_FAULT_MARKERS)


def is_device_fault(e: BaseException) -> bool:
    """Whether an exception is a device memory/allocation fault the
    OOM-degradation ladder may absorb. Deliberately conservative:
    only XlaRuntimeError and EXACTLY RuntimeError (the runtime's and
    the fault hook's type) are eligible — engine control-flow
    exceptions (DcnQueryFailed, MemoryBudgetExceeded, ...) subclass
    RuntimeError and are rejected by the exact-type check even when
    they QUOTE a worker's device-fault text, so a worker-side OOM
    surfaced through the coordinator never triggers a useless
    budget-halved re-run of the whole query. The memory markers must
    match for BOTH types: a non-memory XlaRuntimeError (INVALID_ARGUMENT,
    INTERNAL, ...) is a bug to surface, not a footprint to shrink."""
    if type(e).__name__ != "XlaRuntimeError" and \
            type(e) is not RuntimeError:
        return False
    return text_matches(str(e))
