"""Late materialization for join chains: deferred build-side gathers.

Reference: presto-spi spi/block/DictionaryBlock.java — the reference
engine's joins emit DictionaryBlocks over the build-side PagesIndex
(positions + a shared values block) so carried columns are never copied
per operator; values materialize once, at the first consumer that needs
them. The TPU translation (ROOFLINE.md §4: the join chain is
gather-bound at ~25 M rows/s per carried column, floor = 1 gather per
column per JOIN) replaces the per-join value gathers with ONE int64
row-id indirection column per build side:

  - a join emits its probe columns plus one id Block (build row per
    output row) instead of gathering every carried build column;
  - a downstream join gathers the id column like any probe column, so
    N chained joins COMPOSE the indirection into one id column per
    build side (ids' = ids[probe_idx] — a single gather per side per
    join, independent of how many columns the side carries);
  - join keys a downstream join needs are lifted (gathered) eagerly,
    one column each (``lift_page``);
  - everything else gathers exactly once, at the chain boundary
    (``finish_page``) — the first consumer that needs values (final
    project / aggregation / output).

The executor drives this through ``LazyPage`` items (exec/executor.py
``_lazy_pages`` / ``_join_pass(defer=True)``); pages leaving the join
subtree are always fully materialized, so every other operator is
untouched. ``Block.take`` (page.py) is the shared indirection
primitive.

Physical layout of ``LazyPage.reduced``: the materialized logical
channels in ascending logical order, then ONE id Block per deferred
side (side i at position ``len(mat) + i``). An id Block's ``nulls``
marks rows whose build side is SQL NULL (left-join padding); value
materialization ORs it over the gathered build nulls.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax.numpy as jnp

from presto_tpu.page import Page


@dataclasses.dataclass
class LazySide:
    """One deferred build side: the retained build page plus the map
    from logical output channels to build channels."""

    build: Page
    channel_map: Tuple[Tuple[int, int], ...]  # (logical channel, build ch)


@dataclasses.dataclass
class LazyPage:
    """A join output page with deferred build sides (see module doc)."""

    reduced: Page
    width: int  # logical channel count of the node's output
    mat: Tuple[int, ...]  # materialized logical channels, ascending
    sides: Tuple[LazySide, ...]

    def phys(self, channel: int) -> int:
        """Physical position of a MATERIALIZED logical channel."""
        return self.mat.index(channel)

    def signature(self):
        """Static layout key (jit cache / kernel-recipe identity)."""
        return (
            self.width,
            self.mat,
            tuple(s.channel_map for s in self.sides),
        )


def lift_layout(mat, maps, need):
    """Static recipe shared by ``lift_page`` and the executor's host
    bookkeeping: materializing ``need`` moves those channels into the
    sorted mat set and drops them (and empty sides) from the deferred
    maps. Returns (need, new_mat, new_maps, surviving side indices)."""
    need = tuple(sorted(set(need) - set(mat)))
    new_mat = tuple(sorted(set(mat) | set(need)))
    new_maps = tuple(
        tuple(pair for pair in m if pair[0] not in need) for m in maps
    )
    keep = tuple(i for i, m in enumerate(new_maps) if m)
    return need, new_mat, new_maps, keep


def _side_ids(id_block, build):
    return jnp.clip(
        id_block.data.astype(jnp.int64), 0, build.capacity - 1
    )


def lift_page(mat, maps, need, reduced: Page, *builds) -> Page:
    """Kernel: materialize the ``need`` channels (one gather each) and
    re-emit the reduced page in lift_layout order. Used for downstream
    join keys and filter-referenced channels — the liveness-driven
    eager subset of the ISSUE's contract."""
    need, new_mat, new_maps, keep = lift_layout(mat, maps, need)
    nm = len(mat)
    got = {}
    for si, (m, build) in enumerate(zip(maps, builds)):
        id_block = reduced.blocks[nm + si]
        wanted = [pair for pair in m if pair[0] in need]
        if not wanted:
            continue
        ids = _side_ids(id_block, build)
        for oc, bc in wanted:
            got[oc] = build.blocks[bc].take(
                ids, extra_nulls=id_block.nulls
            )
    blocks = []
    for c in new_mat:
        if c in got:
            blocks.append(got[c])
        else:
            blocks.append(reduced.blocks[mat.index(c)])
    for si in keep:
        blocks.append(reduced.blocks[nm + si])
    return Page(blocks=tuple(blocks), valid=reduced.valid)


def finish_page(mat, maps, width, reduced: Page, *builds) -> Page:
    """Kernel: full materialization at the chain boundary — every
    deferred column gathers exactly ONCE through its side's composed
    id column; materialized channels pass through."""
    blocks = [None] * width
    for i, c in enumerate(mat):
        blocks[c] = reduced.blocks[i]
    nm = len(mat)
    for si, (m, build) in enumerate(zip(maps, builds)):
        id_block = reduced.blocks[nm + si]
        ids = _side_ids(id_block, build)
        for oc, bc in m:
            blocks[oc] = build.blocks[bc].take(
                ids, extra_nulls=id_block.nulls
            )
    assert all(b is not None for b in blocks)
    return Page(blocks=tuple(blocks), valid=reduced.valid)
