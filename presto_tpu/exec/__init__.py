"""Execution layer: physical plans interpreted as chains of jitted
page-at-a-time kernels.

Reference: presto-main sql/planner/LocalExecutionPlanner.java turns a plan
fragment into DriverFactory pipelines of Operators; operator/Driver.java
moves Pages between them. Here the "driver loop" is Python host code making
control decisions (capacity retries, partial-aggregation flushes, build-side
sizing) *between* statically-shaped jitted kernels — XLA program order
replaces the needsInput()/addInput()/getOutput() protocol inside a stage.
"""

from presto_tpu.exec.plan import (  # noqa: F401
    AggSpec,
    Aggregation,
    Filter,
    HashJoin,
    Limit,
    Output,
    PhysicalNode,
    Project,
    Sort,
    TableScan,
    TopN,
    Values,
)
from presto_tpu.exec.executor import Executor  # noqa: F401
