"""Column pruning over physical plans.

Reference: presto-main sql/planner/optimizations/PruneUnreferencedOutputs
(plus the Prune*Columns iterative rules). Walks the plan top-down with the
set of channels the parent needs, narrows every node to just those, and
remaps channel references. The big win is at TableScan: unreferenced
columns are never generated/read at all (the TPC-H connector prunes
generation per column, so this feeds straight through to device work).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from presto_tpu import types as T
from presto_tpu.exec import plan as P
from presto_tpu.expr import ir


def _expr_refs(e: ir.RowExpression, out: Set[int]):
    if isinstance(e, ir.InputRef):
        out.add(e.channel)
    for c in e.children():
        _expr_refs(c, out)


def _remap(e: ir.RowExpression, m: Dict[int, int]) -> ir.RowExpression:
    if isinstance(e, ir.InputRef):
        return ir.InputRef(m[e.channel], e.type)
    if isinstance(e, ir.Call):
        return ir.Call(e.name, tuple(_remap(a, m) for a in e.args), e.type)
    if isinstance(e, ir.SpecialForm):
        return ir.SpecialForm(
            e.form, tuple(_remap(a, m) for a in e.args), e.type
        )
    return e


def expr_channels(e: ir.RowExpression) -> Set[int]:
    """Input channels an expression reads — the per-consumer column
    LIVENESS set. The late-materialization driver (exec/executor.py
    ``_lazy_filter`` / ``_join_pass``) uses it to lift exactly the
    deferred channels a filter predicate or downstream join key
    actually needs as VALUES, leaving everything else as a row-id
    indirection until the chain boundary (exec/latemat.py)."""
    out: Set[int] = set()
    _expr_refs(e, out)
    return out


def remap_expr(e: ir.RowExpression, m: Dict[int, int]) -> ir.RowExpression:
    """Rewrite an expression's InputRefs through a logical->physical
    channel mapping (the lazy reduced-page layout, or any pruned
    layout). Shared by _prune above and the lazy-filter driver."""
    return _remap(e, m)


def _channel_count(node: P.PhysicalNode, counts: Dict) -> int:
    """Output channel count without connector metadata."""
    if node in counts:
        return counts[node]
    if isinstance(node, P.TableScan):
        n = len(node.columns)
    elif isinstance(node, P.Values):
        n = len(node.types)
    elif isinstance(node, P.Project):
        n = len(node.exprs)
    elif isinstance(node, P.Aggregation):
        n = len(node.group_channels) + len(node.aggregates)
    elif isinstance(node, P.HashJoin):
        if node.join_type in ("semi", "anti"):
            n = _channel_count(node.left, counts) + 1
        else:
            n = _channel_count(node.left, counts) + _channel_count(
                node.right, counts)
    elif isinstance(node, P.CrossJoin):
        n = _channel_count(node.left, counts) + _channel_count(
            node.right, counts)
    elif isinstance(node, (P.UniqueId, P.GroupId)):
        n = _channel_count(node.source, counts) + 1
    elif isinstance(node, P.Unnest):
        n = _channel_count(node.source, counts) + 1 + int(
            node.with_ordinality)
    elif isinstance(node, P.Union):
        n = _channel_count(node.sources[0], counts)
    elif isinstance(node, P.Window):
        n = _channel_count(node.source, counts) + len(node.functions)
    elif isinstance(node, P.MarkDistinct):
        n = _channel_count(node.source, counts) + len(
            node.mark_channel_sets)
    elif isinstance(node, (P.Filter, P.Sort, P.TopN, P.Limit, P.Output)):
        n = _channel_count(node.children()[0], counts)
    else:
        raise TypeError(f"unknown node: {node!r}")
    counts[node] = n
    return n


def output_types(node: P.PhysicalNode, catalogs: Dict) -> List[T.SqlType]:
    """Channel types without an Executor (needed for type-correct
    alignment projections during pruning)."""
    if isinstance(node, P.TableScan):
        schema = catalogs[node.catalog].table_schema(node.table)
        return [schema.column_type(c) for c in node.columns]
    if isinstance(node, P.Values):
        return list(node.types)
    if isinstance(node, P.Project):
        return [e.type for e in node.exprs]
    if isinstance(node, P.Aggregation):
        from presto_tpu.exec import agg_states as AS

        src = output_types(node.source, catalogs)
        out = [src[c] for c in node.group_channels]
        for spec in node.aggregates:
            in_t = None if spec.channel is None else src[spec.channel]
            out.append(AS.result_type(spec.function, in_t))
        return out
    if isinstance(node, P.HashJoin):
        left = output_types(node.left, catalogs)
        if node.join_type in ("semi", "anti"):
            return left + [T.BOOLEAN]
        return left + output_types(node.right, catalogs)
    if isinstance(node, P.CrossJoin):
        return output_types(node.left, catalogs) + output_types(
            node.right, catalogs)
    if isinstance(node, (P.UniqueId, P.GroupId)):
        return output_types(node.source, catalogs) + [T.BIGINT]
    if isinstance(node, P.Unnest):
        out = output_types(node.source, catalogs) + [node.element_type]
        if node.with_ordinality:
            out.append(T.BIGINT)
        return out
    if isinstance(node, P.Union):
        return output_types(node.sources[0], catalogs)
    if isinstance(node, P.Window):
        from presto_tpu.ops import window as W

        src = output_types(node.source, catalogs)
        out = list(src)
        for fn in node.functions:
            in_t = None if fn.arg_channel is None else src[fn.arg_channel]
            out.append(W.result_type(fn, in_t))
        return out
    if isinstance(node, P.MarkDistinct):
        return output_types(node.source, catalogs) + [
            T.BOOLEAN for _ in node.mark_channel_sets
        ]
    if isinstance(node, (P.Filter, P.Sort, P.TopN, P.Limit, P.Output)):
        return output_types(node.children()[0], catalogs)
    raise TypeError(f"unknown node: {node!r}")


def prune_plan(node: P.Output, catalogs: Dict) -> P.Output:
    counts: Dict = {}
    ctx = {"counts": counts, "catalogs": catalogs}
    nch = _channel_count(node.source, counts)
    source, mapping = _prune(node.source, set(range(nch)), ctx)
    # Output needs every channel in original order
    assert all(c in mapping for c in range(nch))
    if any(mapping[c] != c for c in range(nch)):
        # restore order via projection (cannot happen today — kept as a
        # safety net for future node kinds)
        raise AssertionError("output channel order changed by pruning")
    return P.Output(source, node.names)


def _prune(node: P.PhysicalNode, needed: Set[int], ctx: Dict):
    """Returns (new_node, mapping old_channel -> new_channel) covering at
    least `needed`."""
    counts = ctx["counts"]
    if isinstance(node, P.TableScan):
        keep = sorted(needed or {0})  # a Page needs >= 1 column
        cols = tuple(node.columns[c] for c in keep)
        return (
            P.TableScan(node.catalog, node.table, cols),
            {c: i for i, c in enumerate(keep)},
        )
    if isinstance(node, P.Values):
        keep = sorted(needed or {0})
        types = tuple(node.types[c] for c in keep)
        rows = tuple(tuple(r[c] for c in keep) for r in node.rows)
        return P.Values(types, rows), {c: i for i, c in enumerate(keep)}
    if isinstance(node, P.Project):
        keep = sorted(needed)
        child_needed: Set[int] = set()
        for c in keep:
            _expr_refs(node.exprs[c], child_needed)
        src, m = _prune(node.source, child_needed, ctx)
        exprs = tuple(_remap(node.exprs[c], m) for c in keep)
        return P.Project(src, exprs), {c: i for i, c in enumerate(keep)}
    if isinstance(node, P.Filter):
        child_needed = set(needed)
        _expr_refs(node.predicate, child_needed)
        src, m = _prune(node.source, child_needed, ctx)
        return P.Filter(src, _remap(node.predicate, m)), m
    if isinstance(node, P.Aggregation):
        nkeys = len(node.group_channels)
        # all group keys stay (they define grouping); agg outputs prune
        keep_aggs = sorted(
            i for i in range(len(node.aggregates))
            if (nkeys + i) in needed
        )
        child_needed = set(node.group_channels)
        for i in keep_aggs:
            ch = node.aggregates[i].channel
            if ch is not None:
                child_needed.add(ch)
            if node.aggregates[i].mask is not None:
                child_needed.add(node.aggregates[i].mask)
            child_needed.update(node.aggregates[i].extra_channels)
        src, m = _prune(node.source, child_needed, ctx)
        groups = tuple(m[c] for c in node.group_channels)
        aggs = tuple(
            P.AggSpec(
                node.aggregates[i].function,
                None if node.aggregates[i].channel is None
                else m[node.aggregates[i].channel],
                None if node.aggregates[i].mask is None
                else m[node.aggregates[i].mask],
                extra_channels=tuple(
                    m[c] for c in node.aggregates[i].extra_channels
                ),
                params=node.aggregates[i].params,
            )
            for i in keep_aggs
        )
        mapping = {c: i for i, c in enumerate(range(nkeys))}
        for out_pos, i in enumerate(keep_aggs):
            mapping[nkeys + i] = nkeys + out_pos
        return (
            P.Aggregation(src, groups, aggs, node.capacity),
            mapping,
        )
    if isinstance(node, P.HashJoin):
        nleft = _channel_count(node.left, counts)
        if node.join_type in ("semi", "anti"):
            left_needed = {c for c in needed if c < nleft}
            left_needed.update(node.left_keys)
            right_needed = set(node.right_keys)
            lsrc, lm = _prune(node.left, left_needed, ctx)
            rsrc, rm = _prune(node.right, right_needed, ctx)
            new_nleft = len(lm)
            join = P.HashJoin(
                lsrc, rsrc,
                tuple(lm[c] for c in node.left_keys),
                tuple(rm[c] for c in node.right_keys),
                node.join_type,
            )
            mapping = dict(lm)
            mapping[nleft] = new_nleft  # match channel
            return join, mapping
        left_needed = {c for c in needed if c < nleft}
        left_needed.update(node.left_keys)
        right_needed = {c - nleft for c in needed if c >= nleft}
        right_needed.update(node.right_keys)
        lsrc, lm = _prune(node.left, left_needed, ctx)
        rsrc, rm = _prune(node.right, right_needed, ctx)
        new_nleft = len(lm)
        join = P.HashJoin(
            lsrc, rsrc,
            tuple(lm[c] for c in node.left_keys),
            tuple(rm[c] for c in node.right_keys),
            node.join_type,
        )
        mapping = dict(lm)
        for c, nc in rm.items():
            mapping[nleft + c] = new_nleft + nc
        return join, mapping
    if isinstance(node, P.CrossJoin):
        nleft = _channel_count(node.left, counts)
        left_needed = {c for c in needed if c < nleft} or {0}
        right_needed = {c - nleft for c in needed if c >= nleft} or {0}
        lsrc, lm = _prune(node.left, left_needed, ctx)
        rsrc, rm = _prune(node.right, right_needed, ctx)
        new_nleft = len(lm)
        mapping = dict(lm)
        for c, nc in rm.items():
            mapping[nleft + c] = new_nleft + nc
        return P.CrossJoin(lsrc, rsrc), mapping
    if isinstance(node, P.UniqueId):
        nsrc = _channel_count(node.source, counts)
        child_needed = {c for c in needed if c < nsrc}
        src, m = _prune(node.source, child_needed, ctx)
        mapping = dict(m)
        mapping[nsrc] = len(m)  # id channel
        return P.UniqueId(src), mapping
    if isinstance(node, P.GroupId):
        nsrc = _channel_count(node.source, counts)
        child_needed = (
            {c for c in needed if c < nsrc} | set(node.key_channels)
        )
        src, m = _prune(node.source, child_needed, ctx)
        mapping = dict(m)
        mapping[nsrc] = len(m)  # gid channel
        return (
            P.GroupId(src, tuple(m[c] for c in node.key_channels),
                      node.set_masks),
            mapping,
        )
    if isinstance(node, P.Unnest):
        nsrc = _channel_count(node.source, counts)
        child_needed = (
            {c for c in needed if c < nsrc} | {node.array_channel}
        )
        src, m = _prune(node.source, child_needed, ctx)
        mapping = dict(m)
        mapping[nsrc] = len(m)  # element channel
        if node.with_ordinality:
            mapping[nsrc + 1] = len(m) + 1
        return (
            P.Unnest(src, m[node.array_channel], node.element_type,
                     node.with_ordinality),
            mapping,
        )
    if isinstance(node, P.Union):
        keep = sorted(needed)
        new_sources = []
        for child in node.sources:
            child_types = output_types(child, ctx["catalogs"])
            src, m = _prune(child, set(keep), ctx)
            # children may retain different extra channels (join/sort keys
            # in their own subtrees) — align every child to exactly `keep`
            if sorted(m) != keep or [m[c] for c in keep] != list(
                    range(len(keep))):
                exprs = tuple(
                    ir.InputRef(m[c], child_types[c]) for c in keep
                )
                src = P.Project(src, exprs)
            new_sources.append(src)
        return (
            P.Union(tuple(new_sources)),
            {c: i for i, c in enumerate(keep)},
        )
    if isinstance(node, (P.Sort, P.TopN)):
        child_needed = set(needed)
        for k in node.keys:
            child_needed.add(k.channel)
        src, m = _prune(node.source, child_needed, ctx)
        from presto_tpu.ops.sort import SortKey

        keys = tuple(
            SortKey(m[k.channel], k.ascending, k.nulls_first)
            for k in node.keys
        )
        if isinstance(node, P.TopN):
            return P.TopN(src, keys, node.limit), m
        return P.Sort(src, keys), m
    if isinstance(node, P.Limit):
        src, m = _prune(node.source, needed, ctx)
        return P.Limit(src, node.count, node.offset), m
    if isinstance(node, P.Window):
        import dataclasses as _dc

        from presto_tpu.ops.sort import SortKey

        nsrc = _channel_count(node.source, counts)
        keep_fns = sorted(
            i for i in range(len(node.functions))
            if (nsrc + i) in needed
        )
        child_needed = {c for c in needed if c < nsrc}
        child_needed.update(node.partition_channels)
        child_needed.update(k.channel for k in node.order_keys)
        for i in keep_fns:
            ch = node.functions[i].arg_channel
            if ch is not None:
                child_needed.add(ch)
        src, m = _prune(node.source, child_needed, ctx)
        fns = tuple(
            _dc.replace(
                node.functions[i],
                arg_channel=(
                    None if node.functions[i].arg_channel is None
                    else m[node.functions[i].arg_channel]
                ),
            )
            for i in keep_fns
        )
        new_node = P.Window(
            src,
            tuple(m[c] for c in node.partition_channels),
            tuple(
                SortKey(m[k.channel], k.ascending, k.nulls_first)
                for k in node.order_keys
            ),
            fns,
        )
        new_nsrc = len(m)
        mapping = dict(m)
        for out_pos, i in enumerate(keep_fns):
            mapping[nsrc + i] = new_nsrc + out_pos
        return new_node, mapping
    if isinstance(node, P.MarkDistinct):
        nsrc = _channel_count(node.source, counts)
        keep_marks = sorted(
            i for i in range(len(node.mark_channel_sets))
            if (nsrc + i) in needed
        )
        child_needed = {c for c in needed if c < nsrc}
        for i in keep_marks:
            child_needed.update(node.mark_channel_sets[i])
        src, m = _prune(node.source, child_needed, ctx)
        new_node = P.MarkDistinct(
            src,
            tuple(
                tuple(m[c] for c in node.mark_channel_sets[i])
                for i in keep_marks
            ),
        )
        new_nsrc = len(m)
        mapping = dict(m)
        for out_pos, i in enumerate(keep_marks):
            mapping[nsrc + i] = new_nsrc + out_pos
        return new_node, mapping
    raise TypeError(f"unknown node: {node!r}")
