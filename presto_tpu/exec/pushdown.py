"""Scan-range predicate pushdown — the TupleDomain analog.

Reference: presto-spi spi/predicate/TupleDomain — the engine extracts
conjunctive per-column domains from filters and hands them to connectors
(ConnectorSplitManager / ConnectorPageSourceProvider) so scans skip work.
The TPU translation: a post-plan pass matches Filter(TableScan), derives
closed integer ranges for scan columns from the predicate's conjuncts,
and attaches them to the TableScan as advisory split-pruning hints. The
Filter stays in place (pruning never changes semantics); generator
connectors invert monotonic columns to row ranges and drop whole splits
(connectors/base.GeneratorConnector.prune_splits), the memory connector
consults per-page min/max stats.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from presto_tpu import types as T
from presto_tpu.exec import plan as P
from presto_tpu.expr import ir

_Range = Tuple[Optional[int], Optional[int]]


def _int_domain(t: T.SqlType) -> bool:
    """Types whose engine encoding is a plain integer (bigint/int/date/
    short decimal): range arithmetic on constants is exact for these."""
    if T.is_string(t) or T.is_floating(t):
        return False
    if isinstance(t, T.DecimalType):
        return t.is_short
    try:
        import numpy as np

        return np.issubdtype(np.dtype(t.numpy_dtype), np.integer)
    except (TypeError, AttributeError):  # dict-coded/state types
        return False


def _unit_tag(t: T.SqlType):
    """Encoding unit of an integer-domain type. Pushed constants carry
    the LITERAL'S units while stored stats carry the COLUMN'S (runtime
    comparisons rescale, split pruning cannot), so a range is only
    extractable when both sides use the same unit — e.g. decimal(10,2)
    vs a bare bigint literal is skipped rather than pruned wrongly."""
    if isinstance(t, T.DecimalType):
        return ("dec", t.scale)
    name = type(t).__name__
    if "Date" in name:
        return "date"
    if "Timestamp" in name or "Time" in name:
        return ("time", name)
    return "int"


def _conjuncts(e: ir.RowExpression) -> List[ir.RowExpression]:
    if isinstance(e, ir.SpecialForm) and e.form == ir.AND:
        out: List[ir.RowExpression] = []
        for a in e.args:
            out.extend(_conjuncts(a))
        return out
    return [e]


def _merge(ranges: Dict[int, _Range], ch: int, lo, hi) -> None:
    old_lo, old_hi = ranges.get(ch, (None, None))
    if lo is not None:
        old_lo = lo if old_lo is None else max(old_lo, lo)
    if hi is not None:
        old_hi = hi if old_hi is None else min(old_hi, hi)
    ranges[ch] = (old_lo, old_hi)


def _ref_const(a, b):
    """(InputRef, int Constant) from either argument order; None if the
    pair doesn't match or the domain isn't integral."""
    if isinstance(a, ir.Constant):
        a, b, flipped = b, a, True
    else:
        flipped = False
    if not (isinstance(a, ir.InputRef) and isinstance(b, ir.Constant)):
        return None
    if b.value is None or not isinstance(b.value, int) or isinstance(
        b.value, bool
    ):
        return None
    if not (_int_domain(a.type) and _int_domain(b.type)):
        return None
    if _unit_tag(a.type) != _unit_tag(b.type):
        return None
    return a, b.value, flipped


def extract_ranges(
    predicate: ir.RowExpression, n_channels: int
) -> Dict[int, _Range]:
    """Conjunctive integer ranges per input channel; ignores anything it
    cannot prove (other conjuncts simply contribute no constraint)."""
    ranges: Dict[int, _Range] = {}
    _FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
    for c in _conjuncts(predicate):
        if isinstance(c, ir.SpecialForm) and c.form == ir.BETWEEN:
            v, lo, hi = c.args
            got = _ref_const(v, lo)
            got2 = _ref_const(v, hi)
            if got and got2 and not got[2] and not got2[2]:
                _merge(ranges, got[0].channel, got[1], got2[1])
            continue
        if isinstance(c, ir.SpecialForm) and c.form == ir.IN:
            vals = []
            ref = c.args[0]
            ok = isinstance(ref, ir.InputRef) and _int_domain(ref.type)
            for cand in c.args[1:]:
                got = _ref_const(ref, cand)
                if not got:
                    ok = False
                    break
                vals.append(got[1])
            if ok and vals:
                _merge(ranges, ref.channel, min(vals), max(vals))
            continue
        if not isinstance(c, ir.Call) or len(c.args) != 2:
            continue
        name = c.name
        if name not in ("eq", "lt", "le", "gt", "ge"):
            continue
        got = _ref_const(c.args[0], c.args[1])
        if got is None:
            continue
        ref, v, flipped = got
        if flipped:
            name = _FLIP[name]
        if name == "eq":
            _merge(ranges, ref.channel, v, v)
        elif name == "le":
            _merge(ranges, ref.channel, None, v)
        elif name == "lt":
            _merge(ranges, ref.channel, None, v - 1)
        elif name == "ge":
            _merge(ranges, ref.channel, v, None)
        elif name == "gt":
            _merge(ranges, ref.channel, v + 1, None)
    return {
        ch: r for ch, r in ranges.items()
        if ch < n_channels and r != (None, None)
    }


def push_scan_constraints(node: P.PhysicalNode) -> P.PhysicalNode:
    """Rewrite Filter(TableScan) so the scan carries the extracted column
    ranges (reference: PickTableLayout/AddExchanges consulting
    TupleDomain during planning)."""
    if isinstance(node, P.Filter) and isinstance(node.source, P.TableScan):
        scan = node.source
        ranges = extract_ranges(node.predicate, len(scan.columns))
        if ranges:
            cons = tuple(
                (scan.columns[ch], lo, hi)
                for ch, (lo, hi) in sorted(ranges.items())
            )
            scan = dataclasses.replace(scan, constraint=cons)
            return P.Filter(scan, node.predicate)
        return node
    kids = node.children()
    if not kids:
        return node
    new_kids = tuple(push_scan_constraints(k) for k in kids)
    if new_kids == kids:
        return node
    updates: Dict[str, object] = {}
    names = [f.name for f in dataclasses.fields(node)]
    if "source" in names and len(new_kids) == 1:
        updates["source"] = new_kids[0]
    elif "left" in names and "right" in names and len(new_kids) == 2:
        updates["left"], updates["right"] = new_kids
    elif "sources" in names:
        updates["sources"] = new_kids
    else:  # pragma: no cover - no known multi-child shapes beyond these
        return node
    return dataclasses.replace(node, **updates)
