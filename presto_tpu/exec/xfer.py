"""THE host<->device transfer plane (ISSUE 12): every data-plane
crossing routes through the choke points below, and every crossing
site anywhere in the engine is declared in TRANSFER_REGISTRY.

Reference: the Java engine keeps its data plane inside the operator
tier by construction — Pages move between operators in process memory
and cross a boundary only at the serialized exchange. The TPU build
has a second, sneakier boundary: host RAM <-> HBM, crossed by
`jax.device_put` / `jax.device_get` / numpy coercions on device
values — and before this registry those crossings were scattered,
unmetered, and invisible to the bench ladder ROADMAP item 6 wants to
drive toward zero.

Two sides, one discipline (the QUERY_COUNTERS / LOCK_REGISTRY model):

  static   tools/xfercheck.py sweeps presto_tpu/ for transfer
           primitives and fails the build on any site missing from
           TRANSFER_REGISTRY, any stale registry row, any `data`-plane
           declaration outside DATA_PLANE_MODULES, and any RAW
           primitive inside a data-plane module that does not route
           through the choke points (escape:
           `# xfercheck: raw-ok - <why>` on the call line).
  dynamic  the choke points (`to_host` / `to_device` / `np_host`)
           meter every crossing — bytes, count, wall — onto the
           process totals here AND onto the thread-bound executor's
           registry counters (h2d_bytes / d2h_bytes / h2d_transfers /
           d2h_transfers + the computed transfer_wall_s), and emit an
           `xfer` span (obs.SPAN_KINDS) when that executor is traced,
           so Chrome traces and critical_path() show copy time as its
           own phase.

Sink binding is per-thread (execute()/stream_fragment() install the
running executor via swap_sink), so concurrent per-query executors on
the server never cross-count. The process totals are plain attribute
adds guarded only by the GIL — a lost increment under contention is
an acceptable metric error, never a correctness one (same stance as
the compile-cache counters).

Plane vocabulary for registry rows:
  data     the per-page query path — scan/exchange/spill/replay/
           decode pages of live queries. Only modules listed in
           DATA_PLANE_MODULES may host `data` sites.
  control  setup, admin, diagnostics, plan-time constant folding —
           crossings that never scale with query data volume.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import jax
import numpy as np

# ---------------------------------------------------------------------
# site -> (direction, plane, justification)
#   direction: "h2d" | "d2h" | "h2d+d2h" (the site crosses both ways)
#   plane:     "data" | "control"  (see module docstring)
# Site names are canonical `module[.Class].function` paths under
# presto_tpu/ (tools/xfercheck.py derives them; nested defs/closures
# attribute to their enclosing top-level function, the concheck
# convention). Every row is cross-checked against a real primitive
# call site — stale rows fail the build exactly like stale
# QUERY_COUNTERS entries.
# ---------------------------------------------------------------------
TRANSFER_REGISTRY: Dict[str, Tuple[str, str, str]] = {
    # ---- the choke points themselves (the only raw-primitive sites
    # allowed in data-plane modules without an escape)
    "exec.xfer.to_host": (
        "d2h", "data",
        "THE d2h choke point: pulls a page/pytree to host numpy, "
        "metered (bytes, count, wall, span)"),
    "exec.xfer.to_device": (
        "h2d", "data",
        "THE h2d choke point: stages a host page/pytree (optionally "
        "sharded) onto the device, metered"),
    "exec.xfer.np_host": (
        "d2h", "data",
        "array-granularity d2h view: numpy coercion of one (possibly "
        "device) array, metered only when bytes actually cross"),
    # ---- page construction (the host-values -> device ingest edge)
    "page.Page.from_arrays": (
        "h2d", "data",
        "page construction stages the validity mask onto the device "
        "(column blocks stage via _encode_column) — the ingest "
        "boundary of Values/memory/test pages"),
    "page._encode_column": (
        "h2d", "data",
        "encoded column data/null arrays stage host values onto the "
        "device at page construction"),
    # ---- executor data plane
    "exec.executor.Executor._fused_stream": (
        "h2d", "data",
        "split-batched fused scans stage 2xB int64 split descriptors "
        "per batched launch (start/count vectors, not page data)"),
    "exec.executor._canonical_join_cols": (
        "h2d", "control",
        "dictionary-universe remap LUT embedded at trace time "
        "(escaped raw-ok: constant folding, sized by dictionary "
        "cardinality)"),
    "exec.executor._state_reduce": (
        "h2d", "control",
        "dictionary sort-rank LUTs embedded at trace time for min/max "
        "over dictionary columns (escaped raw-ok)"),
    "exec.executor._unnest_page": (
        "h2d", "control",
        "array-element flattening LUTs embedded at trace time "
        "(escaped raw-ok)"),
    "exec.executor.Executor.pages": (
        "d2h", "data",
        "EXPLAIN ANALYZE row accounting of HOST-served pages reads "
        "the numpy valid mask in place (device pages keep the "
        "deferred num_rows() scalar; a free view, never a copy)"),
    "exec.executor.Executor._pages_impl": (
        "h2d", "data",
        "RemoteSource ingest: deserialized exchange pages stage onto "
        "the device before entering the consumer fragment"),
    "exec.executor.Executor._join_partition_rebalanced": (
        "d2h", "data",
        "grace-join skew rebalance reads per-piece row counts (host "
        "decision point, admissible on the boosted retry path)"),
    "exec.executor.Executor._cached_pages": (
        "d2h", "data",
        "result-cache fragment replay accounting: host-sink hits "
        "serve host pages directly — zero crossings — and read row "
        "counts host-side for the stats plane (d2h on device pages "
        "only); re-staging for device consumers lives in "
        "_stage_replay"),
    "exec.executor.Executor._stage_replay": (
        "h2d", "data",
        "result-cache replay re-stage: stored host pages stage onto "
        "the device for consumers above a non-sink cache point"),
    "dist.executor.DistExecutor._stage_replay": (
        "h2d", "data",
        "mesh-path cache replay re-stage: replayed host pages commit "
        "as mesh-REPLICATED arrays (shard_map consumers with "
        "replicated in_specs need a consistent placement across "
        "every device)"),
    "exec.executor.Executor.ivm_delta_states": (
        "d2h", "data",
        "IVM refresh delta fold: partial-state pages of the delta "
        "window pull to host for persistence as view state "
        "(streaming/ivm.py; O(new rows) per refresh)"),
    "exec.executor.Executor.ivm_fold_finalize": (
        "h2d+d2h", "data",
        "IVM state merge/finalize: persisted host state pages "
        "re-stage for the agg_merge/agg_final kernels (h2d), the "
        "settled state and finalized result pull back for "
        "persistence and row decode (d2h)"),
    "exec.pagestore.PageStore.put": (
        "d2h", "data",
        "host/disk spill tiers pull materialized pages off the device "
        "(SURVEY §6.4 HBM->RAM spill)"),
    "exec.pagestore.PageStore.stream": (
        "h2d", "data",
        "spilled intermediates re-stage onto the device per restream "
        "pass"),
    # ---- result decode (the /v1/statement serialization boundary)
    "page.Page.to_pylist": (
        "d2h", "data",
        "row materialization at the client/test boundary reads the "
        "validity mask (block columns follow via _decode_block)"),
    "page._decode_block": (
        "d2h", "data",
        "column decode at the client/test boundary pulls block "
        "data/null arrays to host"),
    # ---- DCN exchange serialization plane
    "dist.serde._arrays_of": (
        "d2h", "data",
        "page wire format reads block arrays host-side; pages arrive "
        "already host at the process boundary, so bytes cross only "
        "when a caller serializes a device-resident page"),
    "dist.serde.serialize_page": (
        "d2h", "data",
        "null/validity masks of the serialized page, same boundary as "
        "_arrays_of"),
    "dist.spool._block_value_u64": (
        "d2h", "data",
        "spooled-exchange hash partitioning reads key columns of "
        "already-host pages (the one accounted pull is "
        "server.worker._execute_task's to_host)"),
    "dist.spool.row_hash_u64": (
        "d2h", "data",
        "partition-hash driver reads the validity/null masks of "
        "already-host pages"),
    "dist.spool.take_rows_host": (
        "d2h", "data",
        "per-partition compaction gathers rows of already-host pages"),
    "dist.spool.partition_host_page": (
        "d2h", "data",
        "partition split reads the validity mask of already-host "
        "pages"),
    "dist.spool.device_partition_pages": (
        "h2d+d2h", "data",
        "device-tier exchange partitioning: a host-resident input "
        "(cache replay) stages through the choke point, dictionary "
        "value-hash LUTs stage per distinct dictionary — device "
        "pages pass through free (ISSUE 13); the spool-stats plane "
        "(ISSUE 15) pulls the nparts-long per-partition row-count "
        "vector back per page"),
    "dist.spool.spool_blob": (
        "d2h", "data",
        "LAZY spool materialization: device-resident exchange pages "
        "serialize to wire bytes only when an HTTP fetch (DCN-remote "
        "consumer or replay) or budget demotion needs host bytes"),
    # ---- worker task runtime (the one real d2h of the exchange)
    "server.worker.TaskRuntime._run_task": (
        "d2h", "data",
        "fragment output leaves the device exactly once, at the "
        "serialization boundary (spooled and legacy emit paths)"),
    "server.worker.TaskRuntime.serve_cached_fragment": (
        "d2h", "data",
        "fleet cache serve (ISSUE 19): row-count readback of the "
        "replayed pages' validity masks while parking them as a "
        "pre-finished task spool — cached pages are host-resident, "
        "so np_host meters ZERO bytes unless a demoted entry "
        "rehydrated device-side"),
    # ---- distributed executor (mesh staging)
    "dist.executor.DistExecutor._scan_sharded": (
        "h2d", "data",
        "per-round split-start indices stage onto the mesh (D int64s "
        "per round, not page data)"),
    "dist.executor.DistExecutor._fenced": (
        "d2h", "data",
        "CPU-only collective fence: blocks on program outputs to "
        "serialize rendezvous order — a sync, not a copy"),
    "dist.executor._ici_program": (
        "d2h", "data",
        "ICI exchange collective's CPU-only rendezvous fence (ISSUE "
        "18), same sync-not-copy shape as DistExecutor._fenced"),
    "dist.executor.ici_exchange_pages": (
        "h2d", "data",
        "ICI exchange staging: spooled producer pages commit onto "
        "the exchange mesh sharded over axis d (device-resident "
        "pages cross ZERO bytes — the zero-crossing half of the "
        "ledger pin; a host-resident input pays its honest h2d "
        "once) plus replicated dictionary value-hash LUTs"),
    "dist.executor._stack_to_mesh": (
        "h2d+d2h", "data",
        "local pages gather to host (d2h when device-resident) and "
        "re-stage as one mesh-sharded global array (h2d)"),
    "dist.executor.make_mesh": (
        "d2h", "control",
        "numpy object array of device HANDLES for Mesh construction — "
        "no array bytes cross"),
    # ---- diagnostics / timing
    "devsync.drain": (
        "d2h", "control",
        "forced-completion fence for honest timing (bench, "
        "stats_drain): reads ONE element of the last leaf"),
    # ---- trace-time LUT embedding (jnp coercions of host arrays in
    # kernel builders: constant folding sized by dictionary/identity
    # cardinality, never by query data volume)
    "ops.agg._minmax_identity": (
        "h2d", "control",
        "min/max identity scalar embedded at trace time"),
    "ops.compact.concat_all": (
        "h2d", "control",
        "dictionary-code remap LUTs staged when concatenated pages "
        "carry differing dictionaries — sized by dictionary "
        "cardinality, not row count"),
    "ops.keys.equality_encoding": (
        "h2d", "control",
        "dictionary value-identity LUT embedded at trace time"),
    "ops.keys.order_encoding_parts": (
        "h2d", "control",
        "dictionary sort-rank LUT embedded at trace time"),
    "ops.window._one_function": (
        "h2d", "control",
        "dictionary sort-rank LUTs + window identity scalars embedded "
        "at trace time"),
    "connectors.tpch.TpchConnector._gen_nation_at": (
        "h2d", "control",
        "nation->region map (25 entries) embedded into the generator "
        "at trace time"),
    # ---- expression evaluation
    "expr.eval._const_val": (
        "d2h", "control",
        "plan literal -> typed numpy scalar before device staging; "
        "input is a Python constant, never a device array"),
    "expr.functions_ext._string_cast_val": (
        "d2h", "control",
        "CAST-from-string constant folding coerces a host Python "
        "value to numpy"),
    "expr.functions_ext._val_to_pylist": (
        "d2h", "data",
        "host-side lambda evaluation (array higher-order functions) "
        "pulls the element column once per distinct-argument page"),
}

# modules (canonical dotted paths under presto_tpu/) whose crossings
# are per-page query work: `data`-plane registry rows must live here,
# and raw primitives here must route through the choke points above.
DATA_PLANE_MODULES = frozenset({
    "page",
    "exec.executor",
    "exec.pagestore",
    "exec.xfer",
    "dist.executor",
    "dist.serde",
    "dist.spool",
    "cache.store",
    "server.worker",
    "expr.functions_ext",
})


# ------------------------------------------------------ process totals
class _Totals:
    """Process-lifetime transfer tallies (the /metrics, system.metrics
    and loadbench overlay — per-query executors come and go on the
    concurrent server path, the process truth lives here)."""

    __slots__ = ("h2d_bytes", "d2h_bytes", "h2d_transfers",
                 "d2h_transfers", "transfer_wall_s")

    def __init__(self) -> None:
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.h2d_transfers = 0
        self.d2h_transfers = 0
        self.transfer_wall_s = 0.0


_totals = _Totals()
_tls = threading.local()


def process_totals() -> Dict[str, float]:
    """Snapshot of the process-lifetime transfer counters under the
    registry counter names (+ transfer_wall_s)."""
    return {
        "h2d_bytes": _totals.h2d_bytes,
        "d2h_bytes": _totals.d2h_bytes,
        "h2d_transfers": _totals.h2d_transfers,
        "d2h_transfers": _totals.d2h_transfers,
        "transfer_wall_s": round(_totals.transfer_wall_s, 6),
    }


def swap_sink(sink) -> Optional[object]:
    """Install ``sink`` (an Executor, or None) as THIS thread's
    metering target and return the previous one — execute()/
    stream_fragment() bracket their run with a swap/restore pair so
    nested executors and concurrent query threads never cross-count."""
    prev = getattr(_tls, "sink", None)
    _tls.sink = sink
    return prev


def current_sink() -> Optional[object]:
    """THIS thread's metering sink (an Executor, or None). The wire
    plane (dist/serde.py, dist/connpool.py) meters exchange bytes and
    connection reuse onto the same thread-bound sink the transfer
    choke points use, so the registry counters land on whichever
    executor owns the running fragment/query."""
    return getattr(_tls, "sink", None)


def _device_nbytes(tree) -> int:
    """Bytes that would cross d2h: the summed size of device-backed
    (jax.Array) leaves. numpy leaves are already host — zero."""
    n = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            n += leaf.size * leaf.dtype.itemsize
    return n


def _host_nbytes(tree) -> int:
    """Bytes that would cross h2d: the summed size of host (numpy)
    leaves. jax.Array leaves are already device-resident — zero."""
    n = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, np.ndarray):
            n += leaf.size * leaf.dtype.itemsize
    return n


def _meter(direction: str, nbytes: int, wall: float, label: str) -> None:
    if direction == "h2d":
        _totals.h2d_transfers += 1
        _totals.h2d_bytes += nbytes
    else:
        _totals.d2h_transfers += 1
        _totals.d2h_bytes += nbytes
    _totals.transfer_wall_s += wall
    sink = getattr(_tls, "sink", None)
    if sink is None:
        return
    sink.count_transfer(direction, nbytes, wall)
    tr = sink.trace
    if tr is not None:
        t1 = tr.now()
        tr.complete("xfer", f"{direction}:{label}", t1 - wall, t1,
                    bytes=nbytes)
        sink.trace_spans += 1


def to_host(tree, label: str = "page"):
    """Pull a page/pytree to host numpy — THE metered d2h crossing.
    Already-host input passes through with nothing metered (no bytes
    cross), which is what makes host-served cache replays genuinely
    free on the counters."""
    nbytes = _device_nbytes(tree)
    if nbytes == 0:
        return tree
    t0 = time.perf_counter()
    host = jax.device_get(tree)
    _meter("d2h", nbytes, time.perf_counter() - t0, label)
    return host


def to_device(tree, spec=None, label: str = "page"):
    """Stage a host page/pytree onto the device (optionally under a
    Sharding spec) — THE metered h2d crossing. Device-resident leaves
    contribute no bytes (device_put leaves them in place)."""
    nbytes = _host_nbytes(tree)
    t0 = time.perf_counter()
    out = (jax.device_put(tree, spec) if spec is not None
           else jax.device_put(tree))
    if nbytes:
        _meter("h2d", nbytes, time.perf_counter() - t0, label)
    return out


def np_host(arr, label: str = "array"):
    """numpy view of ONE array, metered as d2h only when ``arr`` is
    device-backed — the accounted replacement for the scattered
    `np.asarray(block.data)` host-pull idioms (page decode, wire
    serde, spool partitioning). On an already-host array this is a
    plain np.asarray view: zero copies, zero meters."""
    if isinstance(arr, jax.Array):
        t0 = time.perf_counter()
        out = np.asarray(arr)
        _meter("d2h", out.nbytes, time.perf_counter() - t0, label)
        return out
    return np.asarray(arr)
