"""The shared program-shape bucket ladder.

Presto amortizes per-query codegen by aggressively reusing compiled
artifacts across queries (reference: sql/gen/ExpressionCompiler's
compiled-expression LRU, keyed on canonical expression shape). The
JAX-native analog has two halves: a persistent compilation cache
(presto_tpu/compilecache.py) and — the half that makes the cache
actually HIT — canonicalizing every dynamic capacity the executor
feeds into program shapes onto ONE power-of-two ladder.

Every join build/output capacity, aggregation group capacity,
grace-partition chunk size, fragment buffer size, and boosted-retry
size quantizes through `bucket` below. Two consequences:

  - a retry or a slightly different planner estimate lands on a rung
    an earlier compilation already paid for (same HLO -> engine jit
    cache hit, or at worst a persistent-cache hit instead of a fresh
    XLA compile);
  - distinct program shapes per operator family are bounded by the
    ladder's log2 depth instead of by the number of distinct
    estimates the planner can produce.

The overflow-retry ladder is part of the same contract: a boost
multiplies by BOOST_STEP (a power of two), so a boosted capacity
re-enters the ladder exactly BOOST_STEP.bit_length()-1 rungs up —
never an off-ladder ad-hoc size that would mint a fresh shape.
"""

from __future__ import annotations

# The ladder floor: no operator buffer is ever sized below this many
# slots (tiny shapes cost a full compile each just like big ones).
LADDER_MIN = 8

# Overflow-retry multiplier: each boosted attempt climbs exactly two
# rungs. Shared by Executor.execute() and the worker-fragment
# stream_fragment() path so a retried fragment's shapes coincide with
# a bigger query's first-attempt shapes.
BOOST_STEP = 4


def bucket(n: int, floor: int = LADDER_MIN) -> int:
    """Quantize a capacity/size onto the ladder: the smallest power of
    two >= max(n, floor). THE canonical quantizer — every program-shape
    size in the engine routes through here."""
    n = max(int(n), floor)
    return 1 << (n - 1).bit_length()


def next_bucket(n: int) -> int:
    """The rung strictly above n: where a size that overflowed its
    bucket re-enters the ladder (never an ad-hoc `n * 2`-ish size)."""
    b = bucket(n)
    return b * 2 if b <= n else b


def next_boost(boost: int) -> int:
    """The next rung of the retry ladder (see BOOST_STEP)."""
    return boost * BOOST_STEP


def chunk_bucket(total: int, parts: int, floor: int = 1024) -> int:
    """Per-partition chunk capacity for grace-style partitioned passes
    (aggregation state, join builds, skew-rebalance chunks): ~2x the
    expected total/parts occupancy — absorbing partition-hash
    fluctuation without a boosted retry — quantized to the ladder."""
    return bucket(max(total // max(parts, 1) * 2, floor))


def exchange_partition_cap(capacity: int, nparts: int,
                           boost: int) -> int:
    """Landing capacity of ONE partition page the device repartition
    kernel compacts to (dist/spool.device_partition_pages): the grace-
    chunk sizing scaled by the overflow-retry boost, never past the
    source page's own bucket. Boost is a ladder power of two, so a
    skewed key distribution re-enters exactly BOOST_STEP rungs up —
    the exchange shares the shapes contract of every other buffer."""
    if nparts <= 1:
        return bucket(capacity)
    return min(bucket(capacity),
               chunk_bucket(capacity, nparts) * bucket(boost, 1))


# ------------------------------------------------ device-memory model
# The axon XLA:TPU runtime faults kernels touching >=~4M-row buffers
# (bisected round 4; the reason max_join_build_rows and
# SPLIT_BATCH_ROWS_MAX exist). The memory governor (exec/membudget.py)
# keeps every PLANNED buffer capacity under this line by construction.
DEVICE_FAULT_ROWS = 1 << 22

# Construction headroom under the fault line: governed buffers size to
# at most half of it, so one boosted-retry rung (x4 capped by the
# governor's own chunking) cannot land exactly ON the line.
SAFE_BUFFER_ROWS = DEVICE_FAULT_ROWS >> 1


def buffer_bytes(rows: int, row_bytes: int) -> int:
    """Static footprint of one operator buffer sized for `rows`: the
    capacity quantizes to the ladder first (that IS the allocation the
    executor makes), so the byte model predicts real allocations, not
    raw row counts."""
    return bucket(rows) * max(int(row_bytes), 1)


def parts_for(rows: int, row_bytes: int, rows_cap, bytes_cap,
              max_parts: int = 256) -> int:
    """Grace-partition pass count that keeps ONE pass's materialization
    of `rows` x `row_bytes` under both caps (None = unconstrained).
    Power of two so partition passes land on the shared ladder."""
    need = 1
    b = bucket(rows)
    if rows_cap:
        need = max(need, -(-b // int(rows_cap)))
    if bytes_cap:
        per_row = max(int(row_bytes), 1)
        need = max(need, -(-(b * per_row) // int(bytes_cap)))
    if need <= 1:
        return 1
    return min(bucket(need, floor=2), max_parts)


# --------------------------------------------------- split batching
# Split-batched execution (exec/executor._fused_stream): how many
# splits of a fused scan pipeline fold into ONE XLA program launch.
# The per-LAUNCH tunnel tax (~6ms on axon, ROOFLINE §1) multiplies by
# splits x programs; batching divides the split factor away. 64 bounds
# the tail-batch padding waste (a padded slot still runs the full
# generator) while keeping SF100's ~600 splits at ~10 launches.
SPLIT_BATCH_MAX = 64

# vmapped page-emitting batches materialize [B, n_pad] stacked buffers
# for the whole batch at once; B * n_pad stays under the axon
# >=4M-row kernel fault line (the same ceiling max_join_build_rows
# exists for). The lax.scan paths carry one split at a time and are
# exempt.
SPLIT_BATCH_ROWS_MAX = DEVICE_FAULT_ROWS


def split_batch_bucket(n: int) -> int:
    """Batch-size bucket for split-batched execution: the smallest
    power of two >= n (floor 2, not LADDER_MIN — batch counts are a
    different family from row capacities). Full batches are sized to a
    power of two by the caller, so only the tail batch pads — with
    traced zero row counts that mask every generated row out — and
    distinct batched programs per pipeline are bounded by the ladder's
    log2 depth, composing with the persistent compile cache exactly
    like every other program shape."""
    return bucket(n, floor=2)
