"""Materialized, restreamable intermediate pages.

Reference: presto-main operator/PagesIndex.java (append-only page store
shared across probe passes) and spiller/FileSingleStreamSpiller.java
(serialized pages staged out of memory and read back per merge pass).

The TPU translation has two tiers:

- tier="device": the page list stays resident in HBM. Restreaming is
  free and involves no transfers; every individual page remains small
  (page-capacity granularity), which matters because the XLA:TPU
  runtime on this host faults kernels touching >=~4M-row buffers — a
  page LIST sidesteps that while a single concatenated buffer would
  not.
- tier="host": pages are pulled to host RAM as numpy pytrees
  (jax.device_get) and re-staged with device_put on each stream() —
  the HBM->host-RAM spill of SURVEY §6.4. This is what lets a
  partitioned operator consume an intermediate larger than device
  memory without recomputing the subplan that produced it.
- tier="disk": each page's array leaves write to one .npz file in a
  per-store temp directory (the treedef and static aux — types,
  dictionaries — are tiny and stay in RAM); stream() re-reads and
  re-stages. The FileSingleStreamSpiller analog proper: at SF100 a
  partitioned join's materialized side can exceed host RAM (SURVEY
  §6.4 sizes SF100 lineitem at ~80 GB raw). Files are deleted on
  close()/GC.

Stores are owned by the Executor per query attempt (capacity-boost
retries invalidate them — cached pages may embed overflowed results).
Tier selection is governed: beyond the explicit host/disk spill
thresholds, the device-memory budget (exec/membudget.py) routes any
materialization that cannot stay HBM-resident to the host tier, and
past several budgets' worth to the disk tier — the overflow home that
lets SF100-scale partitioned state exceed both HBM and host RAM.

Shape contract (exec/shapes.py): stores preserve page shapes exactly
across tiers — a restreamed page re-enters the very programs its
first pass compiled. Callers size everything that feeds a store
(grace-partition chunks, compacted build pieces, fold accumulators)
through the shared bucket ladder, so spilled intermediates never
reintroduce off-ladder shapes on the restream path.
"""

from __future__ import annotations

import atexit
import os
import shutil
import tempfile
from typing import Iterator, List, Optional, Set

import jax
import numpy as np

from presto_tpu.exec import xfer as XF
from presto_tpu.page import Page

# Spill directories created by THIS process, removed on close() and —
# as a backstop for paths that bypass close() (a killed query thread, a
# store leaked past interpreter teardown ordering) — swept at process
# exit. Dir names embed the owning pid (presto_tpu_spill_<pid>_...) so
# sweep_stale_spill_dirs can reclaim leftovers of DEAD processes
# without ever touching a live sibling's spill.
_LIVE_DIRS: Set[str] = set()
_SWEPT_ROOTS: Set[str] = set()


@atexit.register
def _exit_sweep() -> None:  # pragma: no cover - interpreter teardown
    for d in list(_LIVE_DIRS):
        shutil.rmtree(d, ignore_errors=True)
    _LIVE_DIRS.clear()


def sweep_stale_spill_dirs(root: Optional[str] = None) -> int:
    """Remove presto_tpu_spill_* dirs under ``root`` (default: the
    system temp dir) whose embedded owner pid is no longer alive —
    leftovers of crashed/killed engine processes. Returns the number of
    directories removed. Live processes' dirs (including ours) are
    never touched."""
    root = root or tempfile.gettempdir()
    removed = 0
    try:
        entries = os.listdir(root)
    except OSError:
        return 0
    for name in entries:
        if not name.startswith("presto_tpu_spill_"):
            continue
        pid_part = name[len("presto_tpu_spill_"):].split("_", 1)[0]
        if not pid_part.isdigit():
            continue  # pre-pid-tagged layout: ownership unknowable
        pid = int(pid_part)
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)
            continue  # owner alive
        except ProcessLookupError:
            pass
        except OSError:
            continue  # owned by another user / undeterminable
        shutil.rmtree(os.path.join(root, name), ignore_errors=True)
        removed += 1
    return removed


class PageStore:
    """Append-once, stream-many page materialization."""

    def __init__(self, tier: str = "device",
                 spill_dir: Optional[str] = None):
        assert tier in ("device", "host", "disk"), tier
        self.tier = tier
        self._pages: List = []
        self.bytes = 0
        self.page_count = 0
        self._dir: Optional[str] = None
        if tier == "disk":
            root = spill_dir or None
            # opportunistic stale-dir sweep, once per root per process
            key = root or tempfile.gettempdir()
            if key not in _SWEPT_ROOTS:
                _SWEPT_ROOTS.add(key)
                sweep_stale_spill_dirs(key)
            self._dir = tempfile.mkdtemp(
                prefix=f"presto_tpu_spill_{os.getpid()}_", dir=root
            )
            _LIVE_DIRS.add(self._dir)

    def put(self, page: Page) -> None:
        from presto_tpu.exec.executor import page_bytes

        self.bytes += page_bytes(page)
        self.page_count += 1
        if self.tier == "host":
            # one bounded D2H transfer per page; the axon runtime
            # degrades post-D2H kernel launches, so callers only pick
            # the host tier when the intermediate cannot stay resident
            self._pages.append(XF.to_host(page, label="spill-host"))
        elif self.tier == "disk":
            host = XF.to_host(page, label="spill-disk")
            leaves, treedef = jax.tree_util.tree_flatten(host)
            path = os.path.join(self._dir, f"p{self.page_count}.npz")
            np.savez(path, **{f"a{i}": leaf
                              for i, leaf in enumerate(leaves)})
            self._pages.append((path, treedef, len(leaves)))
        else:
            self._pages.append(page)

    def put_host(self, host_page) -> None:
        """Append an ALREADY-HOST page pytree with no device-sync API
        in the path (put() routes through xfer.to_host, which concheck
        treats as the device sync it is). The result-cache demotion
        plane runs under the store's lock — concheck's
        blocking-under-lock rule is why this exists: moving
        host_pages() output between tiers must never touch the device."""
        from presto_tpu.exec.executor import page_bytes

        self.bytes += page_bytes(host_page)
        self.page_count += 1
        if self.tier == "disk":
            leaves, treedef = jax.tree_util.tree_flatten(host_page)
            path = os.path.join(self._dir, f"p{self.page_count}.npz")
            np.savez(path, **{f"a{i}": leaf
                              for i, leaf in enumerate(leaves)})
            self._pages.append((path, treedef, len(leaves)))
        else:
            self._pages.append(host_page)

    # ---------------------------------------------------- byte plane
    # The spooled-exchange tier (dist/scheduler.py) stores SERIALIZED
    # pages — the worker's wire blobs — through the same host/disk
    # tiers and spill-dir lifecycle as page pytrees: host tier keeps
    # the bytes resident, disk tier writes one file per blob into the
    # pid-tagged spill dir (swept on close/exit like every spill file).
    # A store holds pages OR blobs, never both.

    def put_bytes(self, blob: bytes) -> None:
        self.bytes += len(blob)
        self.page_count += 1
        if self.tier == "disk":
            path = os.path.join(self._dir, f"b{self.page_count}.bin")
            with open(path, "wb") as f:
                f.write(blob)
            self._pages.append(path)
        else:  # device/host: resident bytes (there is no device blob)
            self._pages.append(blob)

    def blob_at(self, i: int) -> bytes:
        """Random access for token-indexed spool fetch (the consumer's
        at-least-once protocol re-reads arbitrary tokens)."""
        entry = self._pages[i]
        if isinstance(entry, str):
            with open(entry, "rb") as f:
                return f.read()
        return entry

    def host_pages(self) -> List:
        """Host-side page pytrees WITHOUT device staging — the result
        cache's replay/demotion plane: demoting a host-tier store to a
        disk-tier one must not round-trip every page through the
        device (stream() device_puts), and cache replay wants a safe
        host snapshot it can stage lazily. Host tier returns the
        retained pytrees; disk tier loads its spill files; device tier
        returns the device pages as-is (callers on that tier want
        them resident anyway)."""
        if self.tier == "disk":
            out = []
            for path, treedef, n in self._pages:
                with np.load(path) as z:
                    leaves = [z[f"a{i}"] for i in range(n)]
                out.append(
                    jax.tree_util.tree_unflatten(treedef, leaves)
                )
            return out
        return list(self._pages)

    def stream(self) -> Iterator[Page]:
        if self.tier == "host":
            for p in self._pages:
                yield XF.to_device(p, label="restream")
        elif self.tier == "disk":
            for path, treedef, n in self._pages:
                with np.load(path) as z:
                    leaves = [z[f"a{i}"] for i in range(n)]
                yield XF.to_device(
                    jax.tree_util.tree_unflatten(treedef, leaves),
                    label="restream",
                )
        else:
            yield from self._pages

    def close(self) -> None:
        if self._dir is not None:
            shutil.rmtree(self._dir, ignore_errors=True)
            _LIVE_DIRS.discard(self._dir)
            self._dir = None
        self._pages = []

    def __del__(self):  # best-effort file cleanup
        try:
            self.close()
        except Exception:  # noqa: BLE001 - __del__ must never raise
            pass
