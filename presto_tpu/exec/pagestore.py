"""Materialized, restreamable intermediate pages.

Reference: presto-main operator/PagesIndex.java (append-only page store
shared across probe passes) and spiller/FileSingleStreamSpiller.java
(serialized pages staged out of memory and read back per merge pass).

The TPU translation has two tiers:

- tier="device": the page list stays resident in HBM. Restreaming is
  free and involves no transfers; every individual page remains small
  (page-capacity granularity), which matters because the XLA:TPU
  runtime on this host faults kernels touching >=~4M-row buffers — a
  page LIST sidesteps that while a single concatenated buffer would
  not.
- tier="host": pages are pulled to host RAM as numpy pytrees
  (jax.device_get) and re-staged with device_put on each stream() —
  the HBM->host-RAM spill of SURVEY §6.4. This is what lets a
  partitioned operator consume an intermediate larger than device
  memory without recomputing the subplan that produced it.

Stores are owned by the Executor per query attempt (capacity-boost
retries invalidate them — cached pages may embed overflowed results).
"""

from __future__ import annotations

from typing import Iterator, List

import jax

from presto_tpu.page import Page


class PageStore:
    """Append-once, stream-many page materialization."""

    def __init__(self, tier: str = "device"):
        assert tier in ("device", "host"), tier
        self.tier = tier
        self._pages: List = []
        self.bytes = 0
        self.page_count = 0

    def put(self, page: Page) -> None:
        from presto_tpu.exec.executor import page_bytes

        self.bytes += page_bytes(page)
        self.page_count += 1
        if self.tier == "host":
            # one bounded D2H transfer per page; the axon runtime
            # degrades post-D2H kernel launches, so callers only pick
            # the host tier when the intermediate cannot stay resident
            self._pages.append(jax.device_get(page))
        else:
            self._pages.append(page)

    def stream(self) -> Iterator[Page]:
        if self.tier == "host":
            for p in self._pages:
                yield jax.device_put(p)
        else:
            yield from self._pages
