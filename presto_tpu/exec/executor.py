"""Plan interpreter: chains statically-shaped jitted kernels per page, with
host-side control (capacity retries, build sizing, limit accounting) between
kernel launches.

Reference: presto-main operator/Driver.java's processFor loop moving Pages
through operator chains, SqlTaskExecution mapping splits to drivers. The TPU
translation collapses each operator's inner loop into an XLA program; the
Python host plays the Driver role only at blocking boundaries (aggregation
flush, join build, sort) and for the dynamic-cardinality escape hatch
(overflow-retry with doubled capacity, SURVEY §8.2.1).

Jit discipline: every per-page kernel is compiled once per (plan node,
page schema, capacity) and cached — expression trees and plan nodes are
hashable and ride in the jit cache key, which is the reference's
compiled-expression LRU (sql/gen/ExpressionCompiler cache) reborn.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import compilecache as CC
from presto_tpu import types as T
from presto_tpu.exec import counters as CTRS
from presto_tpu.connectors.base import Connector
from presto_tpu.exec import agg_states as S
from presto_tpu.exec import faults as FAULTS
from presto_tpu.exec import latemat as LM
from presto_tpu.exec import membudget as MB
from presto_tpu.exec import plan as P
from presto_tpu.exec import prune as PR
from presto_tpu.exec import shapes as SH
from presto_tpu.exec import xfer as XF
from presto_tpu.expr.eval import evaluate, evaluate_filter
from presto_tpu.ops import agg as A
from presto_tpu.ops import hashing as H
from presto_tpu.ops import hll as HLL
from presto_tpu.ops import join as J
from presto_tpu.ops import keys as K
from presto_tpu.ops.compact import (
    compact_page,
    concat_all,
    gather_rows,
    slice_page,
)
from presto_tpu.ops.sort import sort_page
from presto_tpu.page import Block, Dictionary, Page


# every program-shape size quantizes through the SHARED bucket ladder
# (exec/shapes.py) — the name survives for the dist executor and tests
_next_pow2 = SH.bucket


def _row_bytes(types) -> int:
    """Static per-row footprint of a channel list (spill estimates)."""
    total = 2  # valid bit + null mask, bytewise
    for t in types:
        if isinstance(t, T.DecimalType) and not t.is_short:
            total += 16
        elif isinstance(t, T.HllStateType):
            total += 8 * HLL.WORDS  # packed register words
        elif T.is_string(t):
            total += 4  # dictionary codes
        else:
            try:
                total += np.dtype(t.numpy_dtype).itemsize
            except (TypeError, AttributeError):  # dict-coded/state
                total += 8
    return total


def _canonical_join_cols(
    left_blocks: List[Block], right_blocks: List[Block]
):
    """Equality-encoded uint64 key columns for a join, canonicalizing
    dictionary-coded pairs through a merged host universe so equal strings
    compare equal across differing dictionaries."""
    lcols: List[jnp.ndarray] = []
    rcols: List[jnp.ndarray] = []
    lnulls, rnulls = [], []
    for lb, rb in zip(left_blocks, right_blocks):
        if lb.dictionary is not None or rb.dictionary is not None:
            ld, rd = lb.dictionary, rb.dictionary
            # raw codes are equality-faithful only for a shared dictionary
            # WITHOUT duplicate values; transform-produced dictionaries
            # (substr/lower via _dict_map) map many codes to one value and
            # must go through the merged-universe canonicalization too
            if ld == rd and not (ld is not None and
                                 ld.has_duplicate_values()):
                lcols.append(lb.data.astype(jnp.int64).astype(jnp.uint64))
                rcols.append(rb.data.astype(jnp.int64).astype(jnp.uint64))
            else:
                universe = {}
                for d in (ld, rd):
                    for v in (d.values if d is not None else []):
                        universe.setdefault(v, len(universe))

                def canon(b, d):
                    if d is None or len(d) == 0:
                        return jnp.zeros(b.data.shape, dtype=jnp.uint64)
                    lut = np.array(
                        [universe[v] for v in d.values], np.uint64
                    )
                    codes = jnp.clip(b.data, 0, len(d) - 1)
                    # xfercheck: raw-ok - trace-time LUT embedding
                    return jnp.asarray(lut)[codes]

                lcols.append(canon(lb, ld))
                rcols.append(canon(rb, rd))
            lnulls.append(lb.nulls)
            rnulls.append(rb.nulls)
        else:
            lc = K.equality_encoding(lb)
            rc = K.equality_encoding(rb)
            lcols.extend(lc)
            rcols.extend(rc)
            lnulls.extend([lb.nulls] * len(lc))
            rnulls.extend([rb.nulls] * len(rc))
    return lcols, lnulls, rcols, rnulls


class _FoldBuffer:
    """Bounded incremental merge of partial-state pages: buffered pages
    flush into a single pcap-sized accumulator through a merge-only
    group-by whenever flush_slots accumulate. One implementation shared
    by the single-pass aggregation, the multi-pass partitioned
    aggregation, and the per-partition fold accumulators (reference:
    InMemoryHashAggregationBuilder flushing under memory pressure)."""

    def __init__(self, ex, merge_fn, pcap, max_iters, flush_slots):
        self.ex = ex
        self.merge_fn = merge_fn
        self.pcap = pcap
        self.max_iters = max_iters
        self.flush_slots = flush_slots
        self.acc = None
        self.buf: list = []
        self.slots = 0
        self.saw_input = False

    def add(self, page) -> None:
        self.saw_input = True
        if self.buf and self.slots + page.capacity > self.flush_slots:
            # pre-flush: the merge concat stays bounded by
            # acc + flush_slots + one page, never creeping past it by
            # a whole buffered batch (the governor's fold bound —
            # membudget.py — relies on this)
            self.flush()
        self.buf.append(page)
        self.slots += page.capacity
        if self.slots >= self.flush_slots:
            self.flush()

    def _merged(self):
        pages = ([self.acc] if self.acc is not None else []) + self.buf
        if not pages:
            return None
        merged = concat_all(pages) if len(pages) > 1 else pages[0]
        self.ex._account_page(merged)
        return merged

    def flush(self) -> None:
        merged = self._merged()
        if merged is None:
            return
        out, overflow = self.merge_fn(merged, self.pcap, self.max_iters)
        self.ex._pending_overflow.append(overflow)
        self.acc, self.buf, self.slots = out, [], 0

    def final_merged(self):
        """All remaining state as one page (None if nothing was added)."""
        return self._merged()


class MemoryBudgetExceeded(RuntimeError):
    """Reference: ExceededMemoryLimitException — the query fails rather
    than thrash (SURVEY §6.4: kill-don't-spill is the v1 policy; spill to
    host RAM is the documented follow-up)."""


class QueryDeadlineExceeded(RuntimeError):
    """query_max_run_time expired (reference: QueryTracker's
    enforceTimeLimits failing queries past query.max-run-time). Raised
    at page boundaries in the execute()/stream_fragment() driver loops
    — a compiled program in flight cannot be interrupted, but the query
    can never outlive its deadline by more than one launch."""


# The device-fault classifier lives in exec/faults.py (shared with the
# DCN coordinator so the marker list cannot drift between the local
# OOM-degradation ladder and worker-error recognition); these aliases
# keep the executor's historical private names importable.
_DEVICE_FAULT_MARKERS = FAULTS.DEVICE_FAULT_MARKERS
_is_device_fault = FAULTS.is_device_fault


_donation_warning_filtered = False


def _filter_donation_warning() -> None:
    """One-time (per process) suppression of jax's 'Some donated
    buffers were not usable' UserWarning: a donated input whose
    (shape, dtype) matches no output cannot be reused and jax says so
    per program (e.g. the validity mask of a differently-sized merge
    output) — expected here, not actionable: donation is best-effort
    per buffer by design. Guarded so repeated donated-program cache
    misses never stack duplicate entries onto warnings.filters."""
    global _donation_warning_filtered
    if _donation_warning_filtered:
        return
    import warnings

    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable")
    _donation_warning_filtered = True


def page_bytes(page: Page) -> int:
    """Static page footprint from shapes/dtypes (no device reads)."""
    total = page.valid.shape[0]  # bool valid
    for blk in page.blocks:
        datas = blk.data if isinstance(blk.data, tuple) else (blk.data,)
        for d in datas:
            total += d.size * d.dtype.itemsize
        if blk.nulls is not None:
            total += blk.nulls.shape[0]
    return total


@dataclasses.dataclass
class NodeStats:
    """Per-plan-node execution stats (reference: OperatorStats)."""

    label: str
    wall_s: float = 0.0
    pages: int = 0
    row_counts: list = dataclasses.field(default_factory=list)

    @property
    def rows(self) -> int:
        return sum(int(c) for c in self.row_counts)


class Executor:
    """Reference: LocalQueryRunner's local execution half — interpret a
    physical plan against in-process connectors, no scheduler, no HTTP."""

    def __init__(
        self,
        catalogs: Dict[str, Connector],
        *,
        page_rows: int = 1 << 18,
        use_jit: bool = True,
    ):
        self.catalogs = catalogs
        self.page_rows = page_rows
        self.use_jit = use_jit
        self._jit_cache: Dict = {}
        # Deferred-sync discipline: the TPU runtime (axon) permanently
        # degrades every subsequent kernel launch (~50ms floor) after ANY
        # device->host read, so the hot path must never call bool()/int()/
        # np.asarray on device values. Capacity-overflow flags accumulate
        # here as device scalars and are checked ONCE per execute(); on
        # overflow the whole query re-runs with boosted capacities
        # (SURVEY §8.2.1's compiled-branch escape, moved to query scope).
        self._pending_overflow: List[jnp.ndarray] = []
        self._capacity_boost = 1
        # per-group slot bound for collect-state aggregates (array_agg/
        # map_agg/approx_percentile); session array_agg_max_elements
        self.collect_k = 1024
        self._collect_stats = None  # id(node) -> NodeStats when ANALYZE
        # EXPLAIN ANALYZE wall honesty on axon: drain the device queue
        # after every page so per-node wall_s is real device time (costs
        # ~6ms/page of sync overhead; off by default)
        self.stats_drain = False
        # memory accounting (reference: OperatorContext->QueryContext
        # hierarchy + query.max-memory enforcement): page footprints are
        # computed from STATIC shapes (host arithmetic, never a device
        # read), tracked as a high-water mark per query, and enforced
        # against max_memory_bytes by failing the query rather than
        # thrashing — the reference's kill-don't-spill default.
        self.max_memory_bytes: Optional[int] = None
        self.peak_memory_bytes = 0
        self._live_bytes = 0
        # Partitioned (grace-style) execution — the spill analog (SURVEY
        # §6.4, reference: spiller/* + revocable memory): when a join
        # build or aggregation state estimate exceeds this many bytes, the
        # operator runs in hash-partition passes over its inputs instead
        # of one materialization. Re-scanning per pass is cheap because
        # generator connectors compute pages from row indices ("scan" =
        # "generate", SURVEY §8.2.6); host-page connectors restage from
        # host RAM — which IS the HBM->host-RAM spill.  None = disabled.
        self.spill_bytes: Optional[int] = None
        self.spill_partitions_used = 0  # observability / tests
        # Restreamable intermediates (reference: PagesIndex +
        # FileSingleStreamSpiller): multi-pass operators consume their
        # sources through _source_stream, which materializes EXPENSIVE
        # subtrees (joins/aggs/sorts below) once into a PageStore and
        # restreams, instead of re-executing the subplan per pass.
        # Intermediates estimated above host_spill_bytes stage to host
        # RAM (the HBM->host spill); below it they stay device-resident
        # as a page list. None = host tier disabled.
        self.host_spill_bytes: Optional[int] = None
        # Third tier: intermediates estimated above disk_spill_bytes
        # write to .npz spill files (FileSingleStreamSpiller proper);
        # None = disk tier disabled. spill_path = target directory.
        self.disk_spill_bytes: Optional[int] = None
        self.spill_path: Optional[str] = None
        self._stream_cache: Dict = {}
        self.host_spill_pages = 0  # observability / tests
        self.host_spill_bytes_used = 0
        self.disk_spill_pages = 0
        # Per-partition skew rebalancing (SURVEY §6.7): on boosted
        # retries, inner grace-join partitions chunk their build rows
        # by position instead of growing buffers (join_skew_rebalance
        # session property); skew_chunks_used is observability.
        self.join_skew_rebalance = True
        self.skew_chunks_used = 0
        # Adaptive execution (ISSUE 15, presto_tpu/adaptive/): the
        # stage-boundary re-planner's counters live on the COORDINATOR
        # executor (the scheduler increments them); skew_preengaged is
        # the worker-side hint — observed per-partition skew in an
        # upstream spool pre-engages the position-chunked rebalance at
        # boost 1 instead of discovering the hot key via an overflow
        # retry (skew_preempted counts those engagements).
        self.adaptive_replans = 0
        self.adaptive_dist_flips = 0
        self.adaptive_capacity_seeds = 0
        self.adaptive_replan_rejected = 0
        self.skew_preengaged = False
        self.skew_preempted = 0
        # Hard per-pass row cap for join builds (session property
        # max_join_build_rows): partitions a join whenever the build-side
        # row estimate exceeds it, independent of the byte threshold.
        # Exists because the axon XLA:TPU runtime faults kernels touching
        # >=~4M-row buffers — the byte threshold tunes memory, this tunes
        # the kernel-size ceiling. None = disabled.
        self.max_build_rows: Optional[int] = None
        # Pallas unique-key join fast path (pallas_join_enabled session
        # property); pallas_joins_used is observability for tests
        self.pallas_join = False
        self.pallas_joins_used = 0
        # every Pallas kernel engagement (joins, segmented-reduction
        # aggregation, partition-id exchange hashing) — the device-
        # native tier's overall gauge (ISSUE 18)
        self.pallas_kernels_used = 0
        # mesh all_to_all exchange plane (dist/scheduler.py; mirrored
        # onto the coordinator): exchanges lowered onto the ICI mesh,
        # their send-buffer bytes, and loud fallbacks to the spool plane
        self.ici_exchanges = 0
        self.ici_bytes = 0
        self.mesh_exchange_fallbacks = 0
        # build-free generated joins (generated_join_enabled session
        # property); generated_joins_used is observability for tests
        self.generated_join = True
        self.generated_joins_used = 0
        # Late materialization for join chains (session property
        # late_materialization_enabled; exec/latemat.py): joins emit a
        # row-id indirection per build side instead of gathering every
        # carried column; values gather ONCE at the first consumer that
        # needs them. "auto" engages only on TPU — the win is HBM
        # gather bandwidth (ROOFLINE §4), while the extra per-join
        # programs cost real CPU compile time (same policy as
        # pallas_join_enabled). Direct Executor construction defaults
        # to ON (library users, unit tests); the session layer maps
        # auto per backend. Counters: gathers_deferred = per-page
        # column gathers skipped at join-output time;
        # gathers_materialized = per-page column value gathers actually
        # performed (lift + chain-boundary finish). On a lazy chain,
        # materialized per carried build column per page is exactly 1.
        self.late_mat = True
        self.gathers_deferred = 0
        self.gathers_materialized = 0
        # Whole-pipeline fusion THROUGH partial aggregation (session
        # property fused_partial_agg_enabled): a scan→filter→project→
        # partial-agg chain compiles to ONE XLA program per split
        # (ROOFLINE §4's computed bound for Q1/Q6). "auto" fuses only
        # on TPU — the win is per-launch tunnel overhead, which CPU
        # doesn't pay, while the bigger fused programs cost real CPU
        # compile time (same policy as pallas_join_enabled).
        # fused_partial_aggs counts fused streams built (mirrors
        # generated_joins_used).
        self.agg_fusion = "auto"
        self.fused_partial_aggs = 0
        # Split-batched execution (session property split_batch_size):
        # fold the per-SPLIT driver loop of a fused pipeline into XLA.
        # Fused scan→filter→project→partial-agg chains run a whole
        # batch of splits as ONE program — a lax.scan over split
        # indices with the partial-aggregation state as carry — and
        # page-emitting chains (probe-side join pipelines) vmap the
        # fused body over a [B, n_pad] stacked batch, emitting the
        # batch as one page. Batch sizes quantize onto the shapes.py
        # ladder (one canonical program per bucket); tail batches pad
        # with zero traced row counts (every generated row masks out);
        # overflow flags OR-reduce across the batch into the deferred
        # ladder. "auto" engages on TPU only — the win is the ~6ms
        # per-LAUNCH tunnel tax, which CPU doesn't pay, while the
        # scanned/vmapped programs cost real CPU compile time (the
        # pallas_join_enabled policy); an int forces that max batch.
        # Counters: program_launches = fused-scan program launches
        # this attempt, splits_scanned = real (unpadded) splits they
        # covered — splits_per_launch in EXPLAIN ANALYZE is their
        # ratio. split_batch_fallbacks counts streams that fell back
        # to the per-split loop because the chain did not trace under
        # vmap/scan (diagnostic; never reset).
        self.split_batch = "auto"
        self.program_launches = 0
        self.splits_scanned = 0
        self.split_batch_fallbacks = 0
        # blocking-aggregation sizing heuristics (session properties
        # agg_optimistic_rows / agg_compact_enabled): start group
        # capacities tight and densify join-sparse inputs, both guarded
        # by the overflow-retry ladder
        self.agg_optimistic_rows = 1 << 18
        self.agg_compact = True
        # DCN ingest registry: RemoteSource.key -> callable yielding
        # host pages (reference: ExchangeClient wiring per task)
        self.remote_sources: Dict[str, object] = {}
        # Compile-cost observability (compilecache.py): per-query deltas
        # of the process-wide counters, set by execute() /
        # stream_fragment() and reported through EXPLAIN ANALYZE.
        # programs_compiled counts real XLA backend compiles (a
        # persistent-cache hit is a program_cache_hits instead);
        # compile_wall_s is their summed wall.
        self.programs_compiled = 0
        self.program_cache_hits = 0
        self.compile_wall_s = 0.0
        # Device-memory governor (session property device_memory_budget;
        # exec/membudget.py): every buffer capacity already quantizes
        # onto the shapes.py ladder, so a pipeline's peak live device
        # bytes is computable BEFORE compile — and pipelines that would
        # exceed the budget rewrite into chunked/streaming forms
        # (grace-partition join passes, probe-side position chunking,
        # generation-chunked scans, partitioned aggregation, PageStore
        # host/disk overflow) instead of faulting the device. 0 = auto:
        # real HBM minus headroom on TPU, a generous cap on CPU (tier-1
        # behavior unchanged unless a test forces a tiny budget).
        self.device_memory_budget = 0
        self._budget_resolved: Optional[Tuple] = None
        # fault_rows: per-buffer row-capacity ceiling. None = auto
        # (SAFE_BUFFER_ROWS on TPU — the axon >=4M-row kernel fault,
        # with construction headroom — unlimited elsewhere); 0 = off;
        # an int forces the ceiling (tests, the static audit).
        self.fault_rows: Optional[int] = None
        # memory_chunked_pipelines: governed rewrites this attempt
        # (reset in _begin_attempt, reported in EXPLAIN ANALYZE and
        # BENCH_DETAILS alongside peak_device_bytes)
        self.memory_chunked_pipelines = 0
        # ---- fault tolerance (ISSUE 5: task retry + deadlines + OOM
        # degradation). query_deadline: absolute time.monotonic()
        # deadline set per query by runner.apply_session from the
        # query_max_run_time session property; checked at page
        # boundaries in the execute()/stream_fragment() driver loops.
        self.query_deadline: Optional[float] = None
        # device-OOM degradation: a caught XLA RESOURCE_EXHAUSTED /
        # allocation fault re-enters execution with the resolved
        # device-memory budget halved (the membudget governor then
        # rewrites over-share pipelines into their chunked forms), up
        # to device_oom_attempts times — an HBM-model miss becomes a
        # slow correct query, not a crash. Wired from the
        # task_retry_attempts session property (0 restores raise-
        # through). device_oom_retries is per-query observability.
        self.device_oom_attempts = 2
        self.device_oom_retries = 0
        self._oom_divisor = 1
        # test/chaos hook: raise a synthetic RESOURCE_EXHAUSTED on the
        # next N attempts (FAULT_DEVICE_OOM env seeds subprocess
        # workers; tests set the attribute directly)
        self.inject_device_oom = int(
            os.environ.get("FAULT_DEVICE_OOM", "0")
        )
        # DCN coordinator task recovery, maintained by DcnRunner on ITS
        # executor (lifetime-cumulative, like the join counters):
        # task_retries = fragments re-dispatched to a surviving worker,
        # workers_excluded = nodes dropped from the query's pool.
        self.task_retries = 0
        self.workers_excluded = 0
        # release_skips = dead-worker page-buffer DELETE releases
        # skipped (DcnRunner mirrors its own count here so every
        # counter surface — EXPLAIN ANALYZE, /metrics, system.metrics,
        # analyze_rung — reads one registry off one object;
        # exec/counters.py)
        self.release_skips = 0
        # Coordinator HA (ISSUE 20, dist/checkpoint.py), lifetime-
        # cumulative on the coordinator's executor: journal records
        # published, queries recovered across a restart, dead
        # placements re-dispatched during re-attach, checkpoint
        # records dropped loudly, and remote-cache probes skipped by
        # the deadline-aware retry budget.
        self.checkpoints_written = 0
        self.coordinator_reattaches = 0
        self.reattach_redispatches = 0
        self.checkpoint_drops = 0
        self.probe_deadline_skips = 0
        # Stage-DAG scheduling (ISSUE 7, dist/scheduler.py): the
        # general fragment-DAG coordinator maintains these on ITS
        # executor, lifetime-cumulative like the task-retry counters.
        # stages_scheduled = DAG stages dispatched;
        # spooled_exchange_pages = pages published into worker-side
        # spooled-exchange partitions (summed from task status);
        # nonleaf_replays = lost NON-LEAF tasks re-dispatched to
        # replay from upstream spools (the Tardigrade recovery the
        # PR-5 model could not express); speculative_tasks_won/lost =
        # straggler races where the speculated copy beat / lost to
        # the original placement.
        self.stages_scheduled = 0
        self.spooled_exchange_pages = 0
        self.nonleaf_replays = 0
        self.speculative_tasks_won = 0
        self.speculative_tasks_lost = 0
        # plan_check (exec/plan_check.py): pre-compile verification of
        # the physical plan — schema-consistent edges, ladder/fault-line
        # capacities, canonical jit-key material, split determinism.
        # "auto" = on under pytest and bench --prewarm (the build/test
        # surface), off on the hot serving path; True/False force.
        self.plan_check = "auto"
        # ---- query-lifecycle tracing (ISSUE 9, presto_tpu/obs/).
        # trace: the active obs.QueryTrace, attached per query by the
        # driver (LocalRunner / DcnRunner / worker task runtime) via
        # obs.attach; None = tracing off, and every recording site
        # below guards on that one check — spans record at page/
        # attempt boundaries ONLY, never inside traced code, so jit
        # keys and compiled programs carry no trace state.
        self.trace = None
        # trace_spans: spans this executor recorded into the active
        # trace (per query; the tracing-off test pins it at 0, and
        # obs.finalize settles it to the trace's full span count)
        self.trace_spans = 0
        # listener_errors: EventListener exceptions swallowed by
        # events.dispatch — counted through count_listener_error so a
        # misbehaving listener is visible on every counter surface
        # instead of vanishing (executor lifetime)
        self.listener_errors = 0
        # ---- observed-stats profiles (obs/profile.py): when a
        # ProfileStore is wired (stats_profile_dir session property),
        # execute()/stream_fragment() seed their starting
        # _capacity_boost from the persisted settled bucket of the
        # same (plan fingerprint, connector snapshot) and record the
        # settled bucket + observed cardinalities on success.
        # capacity_boost_retries counts boosted re-entries this query
        # (the number ROADMAP item 4 drives to zero on repeats);
        # profile_store_hits counts seeded starts.
        self.profile_store = None
        self.capacity_boost_retries = 0
        self.profile_store_hits = 0
        # ---- result cache (ISSUE 10, presto_tpu/cache/): when a
        # ResultCache is wired (result_cache_enabled session property
        # -> runner.apply_session, or set directly by library users),
        # execute()/stream_fragment() select the plan's maximal
        # cacheable subtrees as CACHE POINTS (cache/rules.py) and
        # pages() serves those subtrees from the cache — a hit replays
        # stored host pages and skips compile+launch entirely
        # (program_launches stays 0); a miss streams normally while
        # collecting, and publishes ONLY after the attempt completes
        # overflow-free (a truncated page set can never be cached).
        # Counters are lifetime-cumulative like the join counters;
        # /metrics + system.metrics overlay the process-shared store's
        # totals so concurrent per-query executors aggregate.
        self.result_cache = None
        self.result_cache_hits = 0
        self.result_cache_misses = 0
        self.result_cache_evictions = 0
        self.result_cache_invalidations = 0
        # fleet-cache tallies (ISSUE 19), lifetime-cumulative like the
        # four above: warm-start manifest loads/drops (runner boot
        # pass), coordinator-probed remote hits (dist/scheduler.py),
        # and containment-rewrite hits (cache/rules.py subsumption)
        self.cache_warm_loads = 0
        self.cache_manifest_drops = 0
        self.cache_remote_hits = 0
        self.cache_subsumed_hits = 0
        # serve contained filters from wider cached siblings (session
        # result_cache_subsumption; runner.apply_session resolves)
        self.cache_subsumption = False
        # per-query cache-point state: id(subtree) -> (key, node,
        # tables, watermark, snap, family) — node refs held so ids
        # stay stable; inflight guards the miss path's re-entrant
        # pages() call; pending holds completed-but-unpublished
        # streams until the attempt succeeds
        self._cache_points: Dict[int, tuple] = {}
        self._cache_inflight: set = set()
        self._cache_pending: List = []
        # ---- transfer accounting (ISSUE 12, exec/xfer.py): the choke
        # points meter every host<->device crossing onto THIS query's
        # gauges while the executor is the thread-bound sink
        # (execute()/stream_fragment() install it via XF.swap_sink).
        # Per-query, reset at query start like the spill gauges;
        # transfer_wall_s is the float wall surfaced as a computed
        # EXPLAIN ANALYZE entry (the compile_wall_s pattern).
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.h2d_transfers = 0
        self.d2h_transfers = 0
        self.transfer_wall_s = 0.0
        # host-serve sink: ids of the plan nodes whose pages feed
        # ONLY result serialization/decode (the root and its Output
        # pass-through chain) — a cache replay or RemoteSource ingest
        # there serves host pages directly (zero h2d, zero d2h)
        # instead of round-tripping device_put -> decode pull
        # (exec/xfer.py)
        self._host_sink_ids: frozenset = frozenset()
        # ---- device-resident data plane (ISSUE 13). buffer_donation:
        # thread donate_argnums through _jit for the fold-merge /
        # topn-merge accumulator programs so a chained merge reuses
        # its input's HBM in place instead of allocating a fresh
        # accumulator per step (and a boosted retry's re-run reuses
        # rungs, not residue — _begin_attempt drops every donated
        # chain's references). "auto" engages on TPU only (the win is
        # HBM; donation is free but pointless on CPU) — the
        # pallas_join_enabled policy; session prop
        # buffer_donation_enabled forces. buffers_donated counts
        # donated-program invocations this attempt.
        self.buffer_donation = "auto"
        self.buffers_donated = 0
        # device_exchange: spooled-exchange pages partition on DEVICE
        # (dist/spool.device_partition_pages) and spool as device
        # Pages that materialize to host bytes lazily — the ROOFLINE
        # §11 d2h/h2d exchange pair deletes for mesh-local exchanges.
        # "auto" = TPU only (the jitted partition programs cost real
        # CPU compile time for copies CPU barely pays); session prop
        # device_exchange_enabled forces. mesh_local_exchanges counts
        # exchange edges served device/host-direct between same-
        # process placements, skipping serde entirely (executor
        # lifetime, like the spooled-exchange counters).
        self.device_exchange = "auto"
        self.mesh_local_exchanges = 0
        # ---- exchange wire plane (ISSUE 16, dist/serde.py +
        # dist/connpool.py): lifetime counters metered through the
        # thread-bound transfer sink, like the crossings above.
        # exchange_wire_bytes = post-codec blob bytes serialize_page
        # shipped; exchange_raw_bytes = the pre-codec array bytes
        # behind them (ratio = wire compression);
        # exchange_fetch_reused_conns = shuffle-plane requests served
        # on a reused keep-alive connection instead of a fresh TCP
        # connect.
        self.exchange_wire_bytes = 0
        self.exchange_raw_bytes = 0
        self.exchange_fetch_reused_conns = 0
        # ---- streaming subsystem (ISSUE 14, presto_tpu/streaming/ +
        # connectors/stream.py): lifetime counters mirrored onto the
        # executor so every surface (EXPLAIN ANALYZE, /metrics,
        # system.metrics, analyze_rung, loadbench) renders refresh
        # activity. delta_pages_folded = delta partial-state pages an
        # IVM refresh folded into persisted view state (O(new rows)
        # work); ivm_refreshes = incremental refreshes completed;
        # ivm_full_recomputes = refreshes that fell back to a full
        # recompute (non-IVM-safe plan or ivm_enabled=false — loud,
        # never silent); cursor_polls = tailing /v1/statement cursor
        # polls served; stream_appends_seen = append batches the
        # engine observed on append-only stream connectors (write
        # path + tail polls that saw the offset advance).
        self.delta_pages_folded = 0
        self.ivm_refreshes = 0
        self.ivm_full_recomputes = 0
        self.cursor_polls = 0
        self.stream_appends_seen = 0
        # ---- cross-query launch batching (ISSUE 17,
        # server/launch_batcher.py): the concurrent server path
        # attaches ONE process-shared LaunchBatcher to every per-query
        # executor; compatible fused-pipeline launches (same jit-key
        # family + shapes.py bucket) gang into one vmapped device step
        # with in-program per-query demux. cross_query_batching is the
        # tri-state session knob ("auto" = on whenever a batcher is
        # attached — attachment itself is the concurrent-server
        # condition; raw Executors never batch); wait_ms bounds the
        # gather window so a lone query never stalls past it.
        # Counters: cross_query_batches = shared steps this executor
        # dispatched as leader; cross_query_batched_queries = launches
        # it served from a shared batch (leader or follower);
        # batch_gather_wait_ms = summed window wait;
        # queries_per_launch = widest batch ridden (per-query gauge).
        self.launch_batcher = None
        self.cross_query_batching = "auto"
        self.cross_query_batch_wait_ms = 25
        self.cross_query_batches = 0
        self.cross_query_batched_queries = 0
        self.batch_gather_wait_ms = 0
        self.queries_per_launch = 0

    # ------------------------------------------------------------ plumbing
    def count_listener_error(self) -> None:
        """THE sink events.dispatch reports swallowed listener
        exceptions to — a registry counter (exec/counters.py), so a
        misbehaving EventListener shows on /metrics, system.metrics,
        and EXPLAIN ANALYZE instead of disappearing."""
        self.listener_errors += 1

    def count_transfer(self, direction: str, nbytes: int,
                       wall_s: float) -> None:
        """THE sink exec/xfer.py meters crossings to while this
        executor is the thread-bound transfer sink — registry counters
        (exec/counters.py), so every crossing shows on EXPLAIN
        ANALYZE, /metrics, and system.metrics."""
        if direction == "h2d":
            self.h2d_transfers += 1
            self.h2d_bytes += nbytes
        else:
            self.d2h_transfers += 1
            self.d2h_bytes += nbytes
        self.transfer_wall_s += wall_s

    def count_wire(self, wire: int, raw: int) -> None:
        """Registry-counter sink dist/serde.serialize_page meters
        exchange wire bytes to while this executor is the
        thread-bound sink (exec/xfer.py current_sink) — the
        compression-ratio pair every surface renders."""
        self.exchange_wire_bytes += wire
        self.exchange_raw_bytes += raw

    def count_reused_conn(self) -> None:
        """Registry-counter sink for dist/connpool.py: one
        shuffle-plane HTTP request served on a reused keep-alive
        connection."""
        self.exchange_fetch_reused_conns += 1

    def _reset_transfer_gauges(self) -> None:
        """Per-query transfer-gauge reset (execute(),
        stream_fragment(), and the runner's statement-cache hit path
        — a replayed statement reports ZERO crossings)."""
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.h2d_transfers = 0
        self.d2h_transfers = 0
        self.transfer_wall_s = 0.0

    @staticmethod
    def _sink_chain_ids(node) -> frozenset:
        """ids of the nodes whose page streams reach result decode /
        emit untouched: the root plus its Output pass-through chain
        (Output yields its source's pages verbatim) — the places a
        host page can be served without any device consumer ever
        seeing it."""
        ids = {id(node)}
        while isinstance(node, P.Output):
            node = node.source
            ids.add(id(node))
        return frozenset(ids)

    def count_mesh_local(self) -> None:
        """Registry-counter sink for the mesh-local exchange fast path
        (dist/spool.iter_source_pages, the stage scheduler's root
        drain): one same-process exchange edge served Pages directly —
        no HTTP, no serde, and zero metered crossings when the spool
        is device-resident (ISSUE 13)."""
        self.mesh_local_exchanges += 1

    def count_reattach(self) -> None:
        """Registry-counter sink for one query carried across a
        coordinator restart (dist/checkpoint.reattach_query) — either
        the spooled fast path or the re-run-from-SQL rung."""
        self.coordinator_reattaches += 1

    def count_reattach_redispatch(self) -> None:
        """Registry-counter sink for one dead-spool re-dispatch during
        crash re-attach (dist/checkpoint._redispatch_dead): a persisted
        placement stopped answering and its persisted payload was
        re-POSTed onto the live pool."""
        self.reattach_redispatches += 1

    def count_cache_invalidations(self, n: int) -> None:
        """Registry-counter sink for the runner's write-path result-
        cache invalidation (runner._invalidate_caches) — same pattern
        as count_listener_error: the increment lives on the executor
        so every counter surface renders it."""
        self.result_cache_invalidations += n

    # The four streaming sinks below may be hit from CONCURRENT
    # threads (tail-cursor polls on protocol handler threads, the
    # loadbench writer pool) sharing one bootstrap executor: the
    # increments are plain GIL-guarded adds, so a lost increment
    # under contention is an acceptable METRIC error, never a
    # correctness one — the exec/xfer.py process-totals stance.
    def count_ivm_refresh(self, full: bool = False) -> None:
        """Registry-counter sink for streaming/ivm.refresh: one
        incremental refresh completed, or — ``full`` — one loud
        full-recompute fallback (non-IVM-safe plan, or ivm_enabled
        off)."""
        if full:
            self.ivm_full_recomputes += 1
        else:
            self.ivm_refreshes += 1

    def count_delta_pages(self, n: int) -> None:
        """Registry-counter sink for the IVM delta fold: ``n`` delta
        partial-state pages folded into persisted view state this
        refresh (streaming/ivm.refresh)."""
        self.delta_pages_folded += n

    def count_cursor_poll(self) -> None:
        """Registry-counter sink for the tailing /v1/statement cursor
        plane (server/http_server.TailCursor.poll)."""
        self.cursor_polls += 1

    def count_stream_append(self) -> None:
        """Registry-counter sink for append batches observed on
        append-only stream connectors: the runner's INSERT advance
        path and tail polls that saw the offset move."""
        self.stream_appends_seen += 1

    def _trace_operators(self, tr, att_span) -> None:
        """Emit per-plan-node operator spans from the successful
        attempt's EXPLAIN ANALYZE accounting (pages() wall/rows/pages),
        anchored at the attempt start — operator walls are per-node
        totals, so the spans overlap rather than partition the attempt.
        Called once per successful attempt, AFTER the run (the row-
        count sync here is the same one-sync-at-the-end discipline
        execute_with_stats uses)."""
        stats = self._collect_stats
        if not stats:
            return
        for st in stats.values():
            if not isinstance(st, NodeStats):
                continue
            tr.complete("operator", st.label, att_span.t0,
                        att_span.t0 + st.wall_s, parent=att_span,
                        rows=st.rows, pages=st.pages)
            self.trace_spans += 1

    def _seed_profile(self, node) -> Optional[str]:
        """Observed-stats profile seeding (obs/profile.py): start the
        overflow ladder at the SETTLED capacity bucket a previous run
        of this (plan fingerprint, connector snapshot) recorded — the
        repeated query skips the boost climb (`capacity_boost_retries`
        stays 0). Returns the profile key for recording, or None when
        no store is wired."""
        if self.profile_store is None:
            return None
        key = self.profile_store.key(node, self.catalogs)
        prof = self.profile_store.lookup(key)
        if prof and int(prof.get("capacity_boost", 1)) > 1:
            self._capacity_boost = int(prof["capacity_boost"])
            self.profile_store_hits += 1
        return key

    def _record_profile(self, key: str, rows_out: Optional[int],
                        pages_out: Optional[int] = None) -> None:
        """Persist this run's observed stats: the settled capacity
        bucket plus per-operator output cardinalities when the stats
        accounting ran (tracing or EXPLAIN ANALYZE) — ROADMAP item 4's
        replanning input."""
        prof: Dict = {"capacity_boost": self._capacity_boost}
        if rows_out is not None:
            prof["rows_out"] = int(rows_out)
        if pages_out is not None:
            prof["pages_out"] = int(pages_out)
        stats = self._collect_stats
        if stats:
            ops: Dict[str, int] = {}
            for st in stats.values():
                if isinstance(st, NodeStats):
                    ops[st.label] = ops.get(st.label, 0) + st.rows
            prof["operator_rows"] = ops
        self.profile_store.record(key, prof)

    def _plan_check_on(self) -> bool:
        pc = self.plan_check
        if pc in (True, "true", "on"):
            return True
        if pc in (False, "false", "off", 0):
            return False
        env = os.environ.get("PRESTO_TPU_PLAN_CHECK", "").lower()
        if env in ("0", "false", "off"):
            return False  # explicit operator opt-out wins over auto
        # only an explicit opt-IN enables outside pytest — a typo'd
        # env value must not force the verifier onto the serving path
        return bool(os.environ.get("PYTEST_CURRENT_TEST")
                    or env in ("1", "true", "on"))

    def _verify_plan(self, node: P.PhysicalNode) -> None:
        """Run the pre-compile plan verifier when enabled (auto = test
        and prewarm surfaces only — the serving path pays nothing).
        A clean verdict is memoized per (plan object, sizing knobs) —
        retry ladders and repeated executions of one plan re-verify
        nothing; the held references keep id() stable."""
        if not self._plan_check_on():
            return
        key = (id(node), self.device_memory_budget, self.fault_rows,
               self.page_rows)
        cache = getattr(self, "_plan_check_memo", None)
        if cache is None:
            cache = self._plan_check_memo = {}
        if key in cache:
            return
        from presto_tpu.exec import plan_check as PC

        PC.verify(self, node)
        if len(cache) >= 16:
            cache.clear()
        cache[key] = node  # keep the ref so id() cannot be reused

    @staticmethod
    def _tristate_on(mode) -> bool:
        """THE tri-state knob resolution (pallas_join policy): "off"
        never, "force"/"true" always, "auto" on TPU only. One
        resolver so the accepted alias sets cannot drift per knob."""
        if mode in (True, "true", "force"):
            return True
        if mode in (False, None, "false", "off", 0):
            return False
        return jax.default_backend() == "tpu"

    def _donate_on(self) -> bool:
        """buffer_donation_enabled: forcing it on CPU is the test
        path — jax deletes donated inputs on every backend, so
        use-after-donate bugs fail loudly under tier-1 too; auto is
        TPU-only (the win is HBM reuse in place)."""
        return self._tristate_on(self.buffer_donation)

    def _device_exchange_on(self) -> bool:
        """device_exchange_enabled: spooled-exchange pages partition
        on device and spool as device Pages (dist/spool.
        device_partition_pages); auto = TPU only — the partition
        programs cost real CPU compile time for copies the CPU
        backend barely pays (ROOFLINE §11)."""
        return self._tristate_on(self.device_exchange)

    def _pallas_exchange_on(self) -> bool:
        """Pallas partition-id variant of the device repartition
        kernel, behind the pallas_join_enabled knob — but engaged
        ONLY when explicitly forced, never on "auto": the variant's
        hash is deliberately not splitmix64-compatible, and exchange
        routing must agree across every producer of one exchange. A
        per-process backend probe could disagree on a mixed
        CPU+TPU worker pool and silently mis-route co-partitioned
        join keys; "true"/"force" is session-distributed to every
        task payload, so it resolves identically fleet-wide."""
        return self.pallas_join in (True, "force")

    def _pallas_agg_on(self) -> bool:
        """Segmented-reduction Pallas aggregation (ops/pallas_agg.py),
        behind the pallas_join_enabled tri-state. Engaged only when
        explicitly forced, and then always in interpret mode: the
        kernel's in-kernel one-hot dot is unvalidated on hardware
        (pallas_agg.agg_lowers_on_tpu), matching the radix join
        probe's posture. "auto" keeps the jnp segment-op path, which
        computes identical results."""
        return self.pallas_join in (True, "force")

    def _jit(self, key, fn, static_argnums=(), donate_argnums=()):
        """One jit wrapper per CANONICAL program key. Keys name exactly
        the inputs that shape the traced program (the kernel's bound
        args, static sizes, dictionary signatures) and deliberately
        exclude plan-node identity/estimates — two plans that differ
        only in a capacity estimate share one wrapper, and the bucketed
        static sizes (exec/shapes.py) make their programs identical.

        ``donate_argnums`` marks args whose buffer the CALLER provably
        never touches again (fold/topn merge accumulators); when
        donation resolves on (_donate_on) the program reuses that HBM
        in place and the invocation counts on buffers_donated. The
        donated wrapper caches under a salted key so flipping the
        session property mid-executor can never hand a donating
        program to a non-donating call site."""
        if not self.use_jit:
            return fn
        if donate_argnums and self._donate_on():
            dkey = (key, "donate")
            if dkey not in self._jit_cache:
                _filter_donation_warning()
                jitted = jax.jit(fn, static_argnums=static_argnums,
                                 donate_argnums=donate_argnums)

                def counted(*a, _j=jitted, **kw):
                    self.buffers_donated += 1
                    return _j(*a, **kw)

                self._jit_cache[dkey] = counted
            return self._jit_cache[dkey]
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(fn, static_argnums=static_argnums)
        return self._jit_cache[key]

    # ------------------------------------------- device-memory governor
    # floor for OOM-tightened budgets: the governor's sizing math stays
    # sane however many times the ladder halves. Capped at the resolved
    # budget itself so an EXPLICIT tiny test budget is never silently
    # raised back above what the test forced.
    _OOM_BUDGET_FLOOR = 1 << 20

    def _budget(self) -> int:
        """Resolved device-memory budget in bytes (membudget.py): an
        explicit device_memory_budget wins; auto = HBM minus headroom
        on TPU, a generous cap on CPU; a device-OOM retry halves it
        (_tighten_budget) so the governor re-plans chunked. Cached per
        (setting, tightening) — resolution may query device memory
        stats once."""
        key = (self.device_memory_budget, self._oom_divisor)
        if self._budget_resolved is None or self._budget_resolved[0] != key:
            resolved = MB.resolve_budget(self.device_memory_budget)
            floor = min(resolved, self._OOM_BUDGET_FLOOR)
            self._budget_resolved = (
                key,
                max(resolved // self._oom_divisor, floor),
            )
        return self._budget_resolved[1]

    def _tighten_budget(self) -> None:
        """Halve the resolved budget for the next attempt (the device
        itself just proved the HBM model optimistic)."""
        self._oom_divisor = min(self._oom_divisor * 2, 1 << 10)

    def _check_deadline(self) -> None:
        dl = self.query_deadline
        if dl is not None and time.monotonic() > dl:
            raise QueryDeadlineExceeded(
                "query exceeded query_max_run_time (deadline passed "
                f"{time.monotonic() - dl:.2f}s ago)"
            )

    def _absorb_device_fault(self, e: BaseException,
                             oom_left: int) -> int:
        """Shared OOM-degradation gate for the execute()/
        stream_fragment() driver loops: absorb a device fault by
        tightening the budget (the membudget governor re-plans the
        next attempt chunked) and return the decremented retry budget;
        re-raise anything else, or anything once the budget is
        exhausted."""
        if oom_left <= 0 or not _is_device_fault(e):
            raise e
        self.device_oom_retries += 1
        self._tighten_budget()
        return oom_left - 1

    def _maybe_inject_oom(self) -> None:
        """Fault-injection hook for tests/chaos (SURVEY §6.3 extended
        inward): synthesize the device allocator's failure mode so the
        OOM-degradation ladder is exercisable on CPU."""
        if self.inject_device_oom > 0:
            self.inject_device_oom -= 1
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: injected device OOM (fault hook)"
            )

    def _fault_rows(self) -> Optional[int]:
        """Per-buffer row-capacity ceiling for governed sizing: on TPU
        the axon >=4M-row kernel fault line with construction headroom
        (shapes.SAFE_BUFFER_ROWS); None elsewhere. Tests and the static
        audit force it via self.fault_rows."""
        if self.fault_rows is not None:
            return self.fault_rows or None
        return (
            SH.SAFE_BUFFER_ROWS
            if jax.default_backend() == "tpu" else None
        )

    def _governed_target_rows(self, types, count: bool = True,
                              row_bytes: Optional[int] = None) -> int:
        """Generation chunk (page) size for a scan of `types`-typed
        columns: the configured page_rows, shrunk so ONE page buffer
        fits its budget share — the rewrite that lets a Q1/Q6-shaped
        pipeline stream an arbitrarily large table through fixed-size
        resident buffers (the SF100 on-ramp). count=False lets the
        static audit ask without bumping the rewrite counter;
        row_bytes overrides the width (fused chains govern by their
        WIDEST row — a generated-join chain's output page is wider
        than its scan)."""
        cap = MB.rows_cap(
            row_bytes or _row_bytes(types), self._budget(),
            self._fault_rows(), MB.SCAN_SHARE_DIV,
        )
        if cap is None or self.page_rows <= cap:
            return self.page_rows
        if count:
            self.memory_chunked_pipelines += 1
        return max(cap, SH.LADDER_MIN)

    def _join_parts(self, node: P.HashJoin, left_types, right_types,
                    est_build: Optional[int] = None,
                    row_b: Optional[int] = None):
        """Grace-partition pass count for a materialized join build:
        the legacy session thresholds (spill_bytes byte threshold,
        max_join_build_rows kernel ceiling) and the governor's
        model-driven sizing — one pass's build materialization must fit
        its budget share AND stay under the device fault line. Returns
        (parts, governed): governed means the MODEL forced chunking
        beyond what the thresholds asked for. Shared verbatim by the
        static audit (membudget.audit), so prediction and execution
        cannot drift."""
        if not (
            self._keys_partitionable(right_types, node.right_keys)
            and self._keys_partitionable(left_types, node.left_keys)
        ):
            return 1, False
        if est_build is None:
            est_build = self.estimate_rows(node.right)
        if row_b is None:
            row_b = _row_bytes(right_types)
        parts = 1
        if self.spill_bytes is not None:
            parts = self._spill_partitions(est_build * row_b)
        if self.max_build_rows:
            # kernel-size ceiling, independent of the byte threshold
            parts = max(
                parts,
                _next_pow2(-(-est_build // self.max_build_rows)),
            )
        budget = self._budget()
        # est_build * 2: a grace pass sizes its per-pass build chunks
        # with 2x slack over the expected 1/parts occupancy (partition-
        # hash fluctuation, _exec_join_partitioned) — the governed caps
        # must hold for the SLACKED buffer, or a "governed" pass lands
        # right back on the fault line
        gparts = SH.parts_for(
            est_build * 2, row_b,
            rows_cap=self._fault_rows(),
            bytes_cap=budget // MB.BUILD_SHARE_DIV if budget else None,
        )
        return max(parts, gparts), gparts > parts

    def output_types(self, node: P.PhysicalNode) -> List[T.SqlType]:
        """Static output channel types (reference: PlanNode.getOutputSymbols
        + TypeProvider)."""
        if isinstance(node, P.TableScan):
            schema = self.catalogs[node.catalog].table_schema(node.table)
            return [schema.column_type(c) for c in node.columns]
        if isinstance(node, (P.Values, P.RemoteSource)):
            return list(node.types)
        if isinstance(node, (P.Filter, P.Limit, P.Sort, P.TopN, P.Output)):
            return self.output_types(node.source)
        if isinstance(node, P.Project):
            return [e.type for e in node.exprs]
        if isinstance(node, P.Aggregation):
            if node.step == "partial":
                # keys followed by accumulator state columns (reference:
                # AggregationNode.Step.PARTIAL emits intermediate types)
                src = self.output_types(node.source)
                out = [src[c] for c in node.group_channels]
                for spec, in_t in zip(
                    node.aggregates, self._agg_in_types(node)
                ):
                    for st in S.state_layout(spec.function, in_t):
                        out.append(st.type)
                return out
            if node.step == "final":
                origin = self._partial_origin(node)
                src = self.output_types(origin.source)
                out = [
                    self.output_types(node.source)[i]
                    for i in range(len(node.group_channels))
                ]
                for spec in node.aggregates:
                    in_t = (None if spec.channel is None
                            else src[spec.channel])
                    out.append(S.result_type(
                        spec.function, in_t,
                        tuple(src[c] for c in spec.extra_channels),
                    ))
                return out
            src = self.output_types(node.source)
            out = [src[c] for c in node.group_channels]
            for spec in node.aggregates:
                in_t = None if spec.channel is None else src[spec.channel]
                out.append(S.result_type(
                    spec.function, in_t,
                    tuple(src[c] for c in spec.extra_channels),
                ))
            return out
        if isinstance(node, P.Exchange):
            return self.output_types(node.source)
        if isinstance(node, P.MarkDistinct):
            return self.output_types(node.source) + [
                T.BOOLEAN for _ in node.mark_channel_sets
            ]
        if isinstance(node, P.Window):
            from presto_tpu.ops import window as W

            src = self.output_types(node.source)
            out = list(src)
            for fn in node.functions:
                in_t = (
                    None if fn.arg_channel is None else src[fn.arg_channel]
                )
                out.append(W.result_type(fn, in_t))
            return out
        if isinstance(node, P.HashJoin):
            left = self.output_types(node.left)
            if node.join_type in ("semi", "anti"):
                return left + [T.BOOLEAN]
            return left + self.output_types(node.right)
        if isinstance(node, P.CrossJoin):
            return self.output_types(node.left) + self.output_types(
                node.right)
        if isinstance(node, P.UniqueId):
            return self.output_types(node.source) + [T.BIGINT]
        if isinstance(node, P.GroupId):
            return self.output_types(node.source) + [T.BIGINT]
        if isinstance(node, P.Unnest):
            out = self.output_types(node.source) + [node.element_type]
            if node.with_ordinality:
                out.append(T.BIGINT)
            return out
        if isinstance(node, P.Union):
            return self.output_types(node.sources[0])
        raise TypeError(f"unknown node: {node!r}")

    # ------------------------------------------------------------- execute
    def pages(self, node: P.PhysicalNode) -> Iterator[Page]:
        """Stream pages for a node, collecting per-node stats when an
        EXPLAIN ANALYZE run enabled them (reference: OperatorContext
        wall/row accounting feeding PlanPrinter)."""
        # result-cache points (presto_tpu/cache/): a designated
        # cacheable subtree serves from / populates the shared store;
        # the inflight guard lets the miss path re-enter this method
        # for the real stream. One dict probe when caching is on, zero
        # overhead (empty-dict falsy check) when off.
        if self._cache_points:
            entry = self._cache_points.get(id(node))
            if entry is not None and \
                    id(node) not in self._cache_inflight:
                yield from self._cached_pages(node, entry)
                return
        impl = self._pages_impl(node)
        if self._collect_stats is None:
            for page in impl:
                self._account_page(page)
                yield page
            return
        import time as _time

        st = self._collect_stats.setdefault(
            id(node), NodeStats(type(node).__name__)
        )
        while True:
            t0 = _time.perf_counter()
            try:
                page = next(impl)
                if self.stats_drain:
                    # force real completion so wall_s is device time,
                    # not dispatch time (axon: block_until_ready returns
                    # at dispatch; only a D2H read drains the queue).
                    # Every next() ends drained, so the time measured
                    # here is exactly this node's own marginal work.
                    from presto_tpu.devsync import drain as _drain

                    _drain(page)
            except StopIteration:
                st.wall_s += _time.perf_counter() - t0
                break
            st.wall_s += _time.perf_counter() - t0
            st.pages += 1
            # device scalar; resolved after the run (deferred-sync
            # rule). Host-served pages (cache replay / RemoteSource at
            # the host sink) count host-side instead — num_rows() on a
            # numpy page would implicitly re-stage the valid mask, an
            # un-metered crossing the transfer auditor exists to kill
            v = page.valid
            st.row_counts.append(
                int(XF.np_host(v).sum()) if isinstance(v, np.ndarray)
                else page.num_rows())
            self._account_page(page)
            yield page

    def _scan_chain(self, node: P.PhysicalNode, *, through_joins: bool):
        """Walk a Filter/Project/Exchange chain (and, when
        through_joins, generated-join-eligible HashJoins) down to its
        TableScan. THE one chain walker shared by the generated-join
        eligibility check and the fused-pipeline builder. Returns
        (scan, chain top-down) with HashJoins as (node, info) tuples,
        or None when any node breaks the chain."""
        chain: List = []
        cur = node
        while True:
            if isinstance(cur, (P.Filter, P.Exchange, P.Project)):
                chain.append(cur)
                cur = cur.source
            elif through_joins and isinstance(cur, P.HashJoin):
                info = self._generated_join_info(
                    cur, self.output_types(cur.left))
                if info is None:
                    return None
                chain.append((cur, info))
                cur = cur.left
            elif isinstance(cur, P.TableScan):
                return cur, chain
            else:
                return None

    def _fused_stream(self, node: P.PhysicalNode, agg_tail=None,
                      key_extra=None) -> Optional[Iterator[Page]]:
        """Whole-pipeline fusion: when `node` is a chain of Filter /
        Project / Exchange / build-free generated joins over a
        TableScan of an on-device generator, compile the ENTIRE
        per-page pipeline — generation included — into ONE XLA program
        per split and stream its outputs.

        Reference: operator/ScanFilterAndProjectOperator.java fuses
        scan+filter+project for the same reason (avoid materializing
        between operators); the TPU translation goes further and fuses
        the whole driver loop for the chain, so a page pays ONE kernel
        launch instead of one per node (launch overhead ~6ms on the
        axon tunnel dominates small per-node kernels — ROOFLINE.md §4).
        Returns None when the subtree has any non-fusable node.

        ``agg_tail`` extends the fusion THROUGH partial aggregation
        (see _fused_partial_tail): a ("map", fn, None) tail appends a
        plain page transform (global partial states), an
        ("aggflag", fn, merge) tail appends a grouped partial step
        whose overflow flag joins the deferred ladder — scan→filter→
        project→partial-agg in ONE program per split (ROOFLINE §4: ~6
        launches total for Q1 SF1 instead of ~8 per page). ``merge``
        is the state-merge kernel the split-batched scan carries
        partial state through. ``key_extra`` salts the jit key with
        the caller's boost-dependent parameters.

        Split batching (split_batch_size, ROOFLINE §7) then folds the
        per-SPLIT loop itself into XLA: batches of splits run as ONE
        program — lax.scan with the partial-agg state as carry for agg
        tails, a vmapped [B, n_pad] stack emitted as one page for
        page-emitting chains — so the whole multi-split scan phase of
        a Q1/Q6-shaped query pays ceil(splits/B) launches instead of
        one per split."""
        if not self.use_jit:
            return None
        walked = self._scan_chain(node, through_joins=True)
        if walked is None:
            return None
        cur, chain = walked
        # a chain member that is a live result-cache point must stay
        # an observable pages() boundary (fusing through it would
        # bypass _cached_pages entirely — no hit, no population);
        # an INFLIGHT point is its own miss-path collection, where
        # fusion is exactly what we want
        if self._cache_points:
            for link in chain + [cur]:
                n = link[0] if isinstance(link, tuple) else link
                if id(n) in self._cache_points and \
                        id(n) not in self._cache_inflight:
                    return None
        if not chain and agg_tail is None:
            return None  # a bare scan already runs as one program
        conn = self.catalogs[cur.catalog]
        # structural gate: fuse ONLY when pages() is exactly the base
        # per-split generation loop — a connector (or wrapper: caching,
        # DCN hash-split masking, instance-level instrumentation) that
        # overrides pages() transforms the stream in ways inlined
        # generation would silently bypass. Wrappers whose pages() IS
        # the base loop over their own splits() (the worker's
        # round-robin SplitFilterConnector) declare fused_scan_ok —
        # the fused stream respects their splits()/prune_splits().
        base_pages = (
            getattr(type(conn), "pages", None) is Connector.pages
            or getattr(type(conn), "fused_scan_ok", False)
        )
        if not base_pages or "pages" in vars(conn):
            return None
        names = tuple(cur.columns)
        probe = conn.gen_body(cur.table, 8, names)
        if probe is None:
            return None
        schema = conn.table_schema(cur.table)
        scan_types = tuple(schema.column_type(c) for c in names)
        dicts = getattr(conn, "_dicts", {}).get(cur.table, {})
        scan_dicts = tuple(dicts.get(c) for c in names)
        # generation-chunked splits (membudget.py): one split's padded
        # buffer fits its budget share AT THE CHAIN'S WIDEST ROW — a
        # generated-join chain emits left+right columns per slot, so
        # the output page, not the scan, is the binding width
        chain_row_b = max(
            _row_bytes(scan_types), _row_bytes(self.output_types(node))
        )
        splits = conn.splits(
            cur.table,
            self._governed_target_rows(scan_types,
                                       row_bytes=chain_row_b),
        )
        if cur.constraint:
            splits = conn.prune_splits(cur.table, splits, cur.constraint)

        # bottom-up list of page transforms (top-down in `chain`)
        steps: List = []
        for nd in reversed(chain):
            if isinstance(nd, tuple):
                jnode, info = nd
                kern, windowed = self.generated_join_kernel(jnode, info)
                steps.append(("joinw" if windowed else "join", kern))
                self.generated_joins_used += 1
            else:
                fn = _node_replay_fn(nd)
                if fn is not None:
                    steps.append(("map", fn))
        batch_merge = None
        if agg_tail is not None:
            kind, fn, batch_merge = agg_tail
            steps.append((kind, fn))
            self.fused_partial_aggs += 1

        def make_page(datas, valid, n_pad, count):
            # canonical split shape: generation is padded to the ladder
            # bucket; rows past the split's real count mask out here
            # (generators have no bound — the dist scan relies on the
            # same property), so every tail split of every scale factor
            # reuses one program per bucket instead of minting a shape
            valid = valid & (
                jnp.arange(n_pad, dtype=jnp.int64) < count
            )
            return Page(blocks=tuple(
                Block(data=d, type=t, nulls=None, dictionary=dic)
                for d, t, dic in zip(datas, scan_types, scan_dicts)
            ), valid=valid)

        def apply_steps(page, use_steps):
            flags = []
            for kind, fn in use_steps:
                if kind in ("joinw", "aggflag"):
                    page, flag = fn(page)
                    flags.append(flag)
                else:
                    page = fn(page)
            return page, tuple(flags)

        def run_split(gen_fn, n_pad, start, count):
            datas, valid = gen_fn(start)
            return apply_steps(make_page(datas, valid, n_pad, count),
                               steps)

        scan_row_b = chain_row_b

        # cross-query launch batching (ISSUE 17): when the concurrent
        # server attached a LaunchBatcher and the session didn't force
        # it off, per-split launches first offer themselves to the
        # shared batch point — compatible launches from OTHER queries
        # (equal frozen plan nodes hash equal, so identical statements
        # across clients share a key) gang into one vmapped step.
        xq_on = (
            self.launch_batcher is not None
            and self.cross_query_batching not in
            (False, None, "false", "off")
        )

        def make_xq_fn(n_pad, B):
            # shared batched program: generation vmapped over the
            # stacked [B, n_pad] slots, then DEMUXED IN-PROGRAM — the
            # jitted fn returns one (page, flags) pytree per slot, so
            # every ganged query walks away with exactly the page its
            # solo launch would have produced (row parity is
            # structural, not reassembled on the host)
            gen_b = conn.gen_batch(cur.table, n_pad, names)

            def post(datas, valid, count):
                return apply_steps(
                    make_page(datas, valid, n_pad, count), steps)

            def run_xq(starts, counts):
                datas, valid = gen_b(starts)
                out = jax.vmap(post)(datas, valid, counts)
                return tuple(
                    jax.tree_util.tree_map(lambda x, i=i: x[i], out)
                    for i in range(B)
                )

            return run_xq

        def launch_xq(split):
            """Offer one split to the cross-query batch point; returns
            the demuxed page, or None when the solo path should run
            (batching off, oversized bucket, lone leader, or a chain
            that does not trace under vmap)."""
            n_pad = SH.bucket(split.row_count)
            cap = min(SH.SPLIT_BATCH_MAX,
                      SH.SPLIT_BATCH_ROWS_MAX // max(n_pad, 1))
            if cap < 2:
                return None  # one slot already rides the fault line
            gkey = ("xq", node, key_extra, cur.table, n_pad)

            def make_batched(entries):
                # EXACT width, not the split-batch bucket: a rounded-up
                # lane is dead compute the full n_pad rows wide, which
                # on a compute-bound backend erases the dispatch win.
                # Widths are small (cap <= SPLIT_BATCH_MAX) so the
                # per-width program count is bounded and warm after the
                # first gang at each width.
                B = len(entries)
                jkey = ("xq_batch", node, key_extra, cur.table,
                        n_pad, B)
                if jkey not in self._jit_cache:
                    self._jit_cache[jkey] = jax.jit(
                        make_xq_fn(n_pad, B))
                starts = np.zeros(B, np.int64)
                counts = np.zeros(B, np.int64)
                for j, (s0, c0) in enumerate(entries):
                    starts[j] = s0
                    counts[j] = c0
                try:
                    # metered h2d: 2xB int64 slot descriptors per
                    # shared launch (exec/xfer.py choke point),
                    # attributed to the leader
                    out = self._jit_cache[jkey](
                        XF.to_device(starts, label="batch-starts"),
                        XF.to_device(counts, label="batch-starts"))
                except Exception:
                    # conservative escape (the stream_batched shape):
                    # a chain that does not trace under vmap demotes
                    # every participant to its solo path
                    self._jit_cache.pop(jkey, None)
                    self.split_batch_fallbacks += 1
                    raise
                return [out[j] for j in range(len(entries))]

            res = self.launch_batcher.submit(
                gkey, split.start_row, split.row_count, cap,
                self.cross_query_batch_wait_ms, make_batched)
            if res is None:
                return None
            page, flags, width, waited_ms, leader = res
            if leader:
                # ONE launch covers every ganged query — only the
                # leader pays it, so aggregate program_launches
                # measures real dispatches
                self.program_launches += 1
                self.cross_query_batches += 1
            self.cross_query_batched_queries += 1
            self.queries_per_launch = max(
                self.queries_per_launch, width)
            self.batch_gather_wait_ms += int(waited_ms)
            self.splits_scanned += 1
            # this query's slot share of the stacked batch buffer
            self.peak_memory_bytes = max(
                self.peak_memory_bytes, n_pad * scan_row_b
            )
            self._pending_overflow.extend(flags)
            return page

        def launch_one(split):
            solo_mark = contextlib.nullcontext()
            if xq_on:
                page = launch_xq(split)
                if page is not None:
                    return page
                # solo fallthrough still seeds the train: same-key
                # arrivals linger behind this execution exactly as
                # behind a batched step (launch_batcher.solo_inflight)
                n_pad = SH.bucket(split.row_count)
                solo_mark = self.launch_batcher.solo_inflight(
                    ("xq", node, key_extra, cur.table, n_pad))
            n_pad = SH.bucket(split.row_count)
            key = ("fused", node, key_extra, cur.table, n_pad)
            if key not in self._jit_cache:
                gen_fn = conn.gen_body(cur.table, n_pad, names)
                self._jit_cache[key] = jax.jit(
                    functools.partial(run_split, gen_fn, n_pad))
            with solo_mark:
                page, flags = self._jit_cache[key](
                    jnp.int64(split.start_row),
                    jnp.int64(split.row_count),
                )
            # the generation buffer lives INSIDE the fused program and
            # never passes _account_page — account it here so
            # peak_device_bytes stays honest for fused pipelines
            self.peak_memory_bytes = max(
                self.peak_memory_bytes, n_pad * scan_row_b
            )
            self.program_launches += 1
            self.splits_scanned += 1
            self._pending_overflow.extend(flags)
            return page

        live = [s for s in splits if s.row_count]

        def stream_single():
            for split in live:
                yield launch_one(split)

        bmax = 0
        if len(live) > 1:
            n_pad_all = max(SH.bucket(s.row_count) for s in live)
            bmax = self._split_batch_max(
                n_pad_all, scanned=agg_tail is not None,
                row_bytes=chain_row_b)
        if bmax < 2:
            return stream_single()

        # ---------------- split-batched execution (one program per
        # batch of splits; ROOFLINE §7). One canonical program per
        # (pipeline, n_pad, batch bucket): full batches are the pow-2
        # bmax, the tail batch is its own bucket, padded slots carry
        # count=0 so every generated row masks out.
        def or_flags(flags):
            out = jnp.zeros((), dtype=jnp.bool_)
            for f in flags:
                out = out | f
            return out

        def build_batch_fn():
            if agg_tail is None:
                # page-emitting chain: vmap the fused body over the
                # stacked [B, n_pad] batch; the batch emits as ONE
                # page of B*n_pad slots (the exact concatenation of
                # the per-split pages), so downstream per-page
                # programs amortize their launches by B too
                gen_b = conn.gen_batch(cur.table, n_pad_all, names)

                def post(datas, valid, count):
                    return apply_steps(
                        make_page(datas, valid, n_pad_all, count),
                        steps,
                    )

                def run_batch(starts, counts):
                    datas, valid = gen_b(starts)
                    pages, flags = jax.vmap(post)(datas, valid, counts)
                    return (
                        _merge_leading(pages),
                        tuple(jnp.any(f) for f in flags),
                    )

                return run_batch
            gen_fn = conn.gen_body(cur.table, n_pad_all, names)
            if steps[-1][0] == "map":
                # global partial-agg tail: scan over splits, stacking
                # the 1-row state pages — the batch emits exactly the
                # concat of the per-split states, so parity with the
                # unbatched driver loop is bit-exact
                def body(_, x):
                    page, flags = run_split(
                        gen_fn, n_pad_all, x[0], x[1])
                    return 0, (page, or_flags(flags))

                def run_batch(starts, counts):
                    _, (states, flags) = jax.lax.scan(
                        body, 0, (starts, counts))
                    return _merge_leading(states), (jnp.any(flags),)

                return run_batch
            # grouped partial-agg tail: lax.scan over splits with the
            # partial-aggregation STATE as carry — generation,
            # filtering, and accumulation never return to the host.
            # The carry is one merge-capacity state page; each split's
            # partial states fold in through the same merge kernel the
            # host _FoldBuffer uses, and every overflow (agg, join
            # window, merge) ORs into one deferred flag per batch.
            pre = steps[:-1]
            tail_fn = steps[-1][1]

            def one_state(start, count):
                datas, valid = gen_fn(start)
                page, flags = apply_steps(
                    make_page(datas, valid, n_pad_all, count), pre)
                st, ovf = tail_fn(page)
                return st, or_flags(flags) | ovf

            def run_batch(starts, counts):
                # split 0 seeds the carry (merged alone into the carry
                # capacity, so init and body share one state shape)
                st0, f0 = one_state(starts[0], counts[0])
                acc, m0 = batch_merge(st0)

                def body(carry, x):
                    acc, ovf = carry
                    st, f = one_state(x[0], x[1])
                    acc2, mo = batch_merge(concat_all([acc, st]))
                    return (acc2, ovf | f | mo), None

                (acc, ovf), _ = jax.lax.scan(
                    body, (acc, f0 | m0),
                    (starts[1:], counts[1:]),
                )
                return acc, (ovf,)

            return run_batch

        def stream_batched():
            i = 0
            while i < len(live):
                chunk = live[i:i + bmax]
                if len(chunk) == 1:
                    # a lone tail split reuses the per-split program
                    # instead of padding a 2-batch (a padded slot
                    # still runs the full generator)
                    yield launch_one(chunk[0])
                    i += 1
                    continue
                B = SH.split_batch_bucket(len(chunk))
                key = ("fused_batch", node, key_extra, cur.table,
                       n_pad_all, B)
                if key not in self._jit_cache:
                    self._jit_cache[key] = jax.jit(build_batch_fn())
                starts = np.zeros(B, np.int64)
                counts = np.zeros(B, np.int64)
                for j, s in enumerate(chunk):
                    starts[j] = s.start_row
                    counts[j] = s.row_count
                try:
                    # metered h2d: 2xB int64 split descriptors per
                    # batched launch (exec/xfer.py choke point)
                    page, flags = self._jit_cache[key](
                        XF.to_device(starts, label="batch-starts"),
                        XF.to_device(counts, label="batch-starts"))
                except Exception:
                    if i > 0:
                        raise
                    # conservative escape: a chain that does not trace
                    # under vmap/scan (custom kernels, host callbacks)
                    # runs the per-split loop instead — nothing has
                    # been yielded yet, so the stream restarts whole
                    self._jit_cache.pop(key, None)
                    self.split_batch_fallbacks += 1
                    yield from stream_single()
                    return
                self.program_launches += 1
                self.splits_scanned += len(chunk)
                self._pending_overflow.extend(flags)
                # vmapped batches materialize the [B, n_pad] stack;
                # scanned (agg-tail) batches carry one split at a time
                live_rows = (
                    n_pad_all if agg_tail is not None
                    else B * n_pad_all
                )
                self.peak_memory_bytes = max(
                    self.peak_memory_bytes, live_rows * scan_row_b
                )
                yield page
                i += len(chunk)

        return stream_batched()

    def _fused_partial_tail(self, node: P.Aggregation, layouts,
                            cap: Optional[int], max_iters: Optional[int]):
        """The partial-aggregation tail step for _fused_stream — a
        (kind, fn, batch_merge) triple — or None when the shape should
        not fuse. Global aggregations always qualify. Grouped ones
        qualify unless fusing would bypass the join-output compaction
        stream (_agg_source_pages): big group capacity AND a join in
        the chain — there the blocking agg's per-sparse-page cost
        dwarfs the saved launches. Everywhere else the fused tail does
        EXACTLY the per-page work of the unfused driver loop, minus
        the launches. ``batch_merge`` (grouped tails only) is the
        state-merge kernel the split-batched lax.scan carries partial
        state through — the in-program analog of the host
        _FoldBuffer's merge."""
        mode = self.agg_fusion
        if mode in (False, None, "false", "off") or not self.use_jit:
            return None
        if mode == "auto" and jax.default_backend() != "tpu":
            return None
        layouts_t = tuple(tuple(l) for l in layouts)
        if not node.group_channels:
            return ("map", functools.partial(
                _partial_global_agg, node.aggregates, layouts_t), None)
        if cap is None:
            return None
        if (node.capacity > A.MATMUL_AGG_MAX_GROUPS
                and _subtree_has_join(node.source)):
            return None
        pallas = self._pallas_agg_on()
        if pallas:
            self.pallas_kernels_used += 1
        raw = functools.partial(
            _partial_agg_page, node.group_channels, node.aggregates,
            layouts_t, collect_k=self._collect_k_eff,
            pallas_agg=pallas,
        )
        merge_raw = functools.partial(
            _merge_partials_page, node.aggregates, layouts_t,
            len(node.group_channels), collect_k=self._collect_k_eff,
        )
        return (
            "aggflag",
            functools.partial(_fused_agg_step, raw, cap, max_iters),
            functools.partial(_fused_merge_step, merge_raw, cap,
                              max_iters),
        )

    def _split_batch_max(self, n_pad: int, scanned: bool,
                         row_bytes: int = 0) -> int:
        """Effective max splits per batched launch for one fused
        stream, or 0 when split batching is off. split_batch_size
        resolution: "auto" engages on TPU only (the win is the
        per-launch tunnel tax, which CPU doesn't pay, while the
        scanned/vmapped programs cost real CPU compile time — the
        pallas_join_enabled policy); an int forces that max on any
        backend. vmapped page batches (scanned=False) additionally
        bound B*n_pad under the axon kernel fault line; the lax.scan
        agg paths carry one split at a time and are exempt. The
        result is floored to a power of two so full batches land on
        the shapes.py ladder and only the tail batch pads."""
        mode = self.split_batch
        if mode in (False, None, 0, "false", "off", "0"):
            return 0
        if not self.use_jit:
            return 0
        if mode == "auto":
            if jax.default_backend() != "tpu":
                return 0
            cap = SH.SPLIT_BATCH_MAX
        else:
            cap = int(mode)
        if not scanned and n_pad > 0:
            cap = min(cap, SH.SPLIT_BATCH_ROWS_MAX // max(n_pad, 1))
            # governed: the stacked [B, n_pad] batch buffer fits its
            # budget share too (membudget.py), not just the row line
            budget = self._budget()
            if budget and row_bytes:
                cap = min(
                    cap,
                    max((budget // MB.SCAN_SHARE_DIV)
                        // (n_pad * row_bytes), 1),
                )
        if cap < 2:
            return 0
        return 1 << (cap.bit_length() - 1)

    def _pages_impl(self, node: P.PhysicalNode) -> Iterator[Page]:
        if isinstance(node, (P.Filter, P.Project, P.HashJoin)):
            fused = self._fused_stream(node)
            if fused is not None:
                yield from fused
                return
        if isinstance(node, P.TableScan):
            conn = self.catalogs[node.catalog]
            # generation-chunked scan (membudget.py): page size shrinks
            # so one generated buffer fits its budget share — the same
            # stream shape, smaller resident chunks
            yield from conn.pages(
                node.table, node.columns,
                target_rows=self._governed_target_rows(
                    self.output_types(node)
                ),
                constraint=node.constraint,
            )
            return
        if isinstance(node, P.RemoteSource):
            # DCN ingest (reference: ExchangeOperator): the registered
            # supplier yields deserialized host pages; stage on device
            # unless the pages feed only result decode (the host sink)
            serve_host = id(node) in self._host_sink_ids
            for page in self.remote_sources[node.key]():
                yield page if serve_host else XF.to_device(
                    page, label="remote-source")
            return
        if isinstance(node, P.Values):
            cols = list(zip(*node.rows)) if node.rows else [
                [] for _ in node.types
            ]
            yield Page.from_arrays(
                [list(c) for c in cols], list(node.types)
            )
            return
        if isinstance(node, P.Filter):
            fn = self._jit(
                ("filter", node.predicate),
                lambda page: evaluate_filter(node.predicate, page, jnp),
            )
            for page in self.pages(node.source):
                yield fn(page)
            return
        if isinstance(node, P.Project):
            fn = self._jit(
                ("project", node.exprs),
                functools.partial(_project_page, node.exprs),
            )
            for page in self.pages(node.source):
                yield fn(page)
            return
        if isinstance(node, P.Aggregation):
            yield from self._exec_aggregation(node)
            return
        if isinstance(node, P.HashJoin):
            yield from self._exec_join(node)
            return
        if isinstance(node, P.CrossJoin):
            right_pages = list(self.pages(node.right))
            if not right_pages:
                return
            build_all = concat_all(right_pages)
            # modest static build capacity (cross-join output is
            # probe_cap x build_cap — capacity-sized builds would explode
            # quadratically); dropped rows raise the deferred overflow
            # flag and the query retries with boosted capacity
            bcap = min(
                _next_pow2(build_all.capacity),
                _next_pow2(4096 * self._capacity_boost),
            )
            self._pending_overflow.append(build_all.num_rows() > bcap)
            build = compact_page(build_all, bcap)
            fn = self._jit(
                ("cross", build.capacity),
                _cross_join_page,
            )
            for page in self.pages(node.left):
                yield fn(page, build)
            return
        if isinstance(node, P.UniqueId):
            offset = 0
            for page in self.pages(node.source):
                ids = Block(
                    data=jnp.arange(page.capacity, dtype=jnp.int64) + offset,
                    type=T.BIGINT,
                )
                offset += page.capacity
                yield Page(blocks=page.blocks + (ids,), valid=page.valid)
            return
        if isinstance(node, P.Unnest):
            for page in self.pages(node.source):
                dic = page.block(node.array_channel).dictionary
                fn = self._jit(
                    ("unnest", node.array_channel, node.element_type,
                     node.with_ordinality, dic, page.capacity),
                    functools.partial(
                        _unnest_page, node.array_channel,
                        node.element_type, node.with_ordinality,
                    ),
                )
                yield fn(page)
            return
        if isinstance(node, P.GroupId):
            # one replica per grouping set: absent keys nulled, gid
            # appended (reference: GroupIdOperator's page replication)
            fns = [
                self._jit(
                    ("groupid", node.key_channels, mask, si),
                    functools.partial(_group_id_page, node.key_channels,
                                      mask, si),
                )
                for si, mask in enumerate(node.set_masks)
            ]
            for page in self.pages(node.source):
                for fn in fns:
                    yield fn(page)
            return
        if isinstance(node, P.Union):
            for src in node.sources:
                yield from self.pages(src)
            return
        if isinstance(node, P.MarkDistinct):
            pages = list(self.pages(node.source))
            if not pages:
                return
            merged = concat_all(pages) if len(pages) > 1 else pages[0]
            self._account_page(merged)
            fn = self._jit(
                ("markdistinct", node.mark_channel_sets),
                functools.partial(
                    _mark_distinct_page, node.mark_channel_sets
                ),
                static_argnums=(1, 2),
            )
            # boost rides as a static arg so the retry ladder actually
            # deepens probing (a boost baked into the partial would be
            # invisible to the jit cache key)
            out, overflow = fn(
                merged, _next_pow2(merged.capacity),
                64 * self._capacity_boost,
            )
            self._pending_overflow.append(overflow)
            yield out
            return
        if isinstance(node, P.Window):
            from presto_tpu.ops import window as W

            pages = list(self.pages(node.source))
            if not pages:
                return
            merged = concat_all(pages) if len(pages) > 1 else pages[0]
            src_types = self.output_types(node.source)
            out_types = tuple(self.output_types(node)[len(src_types):])
            fn = self._jit(
                ("window", node.partition_channels, node.order_keys,
                 node.functions, out_types, merged.capacity),
                functools.partial(
                    W.window_page, node.partition_channels,
                    node.order_keys, node.functions, out_types,
                ),
            )
            yield fn(merged)
            return
        if isinstance(node, P.TopN):
            # streaming top-N (reference: TopNOperator's bounded heap):
            # per page, keep the local top-N, then merge with the running
            # candidate set — never materializes the whole input
            running = None
            for page in self.pages(node.source):
                local_fn = self._jit(
                    ("topn_local", node.keys, node.limit, page.capacity),
                    functools.partial(sort_page, sort_keys=node.keys,
                                      limit=node.limit),
                )
                local = local_fn(page)
                if running is None:
                    running = local
                    continue
                merge_fn = self._jit(
                    ("topn_merge", node.keys, node.limit,
                     running.capacity, local.capacity),
                    functools.partial(_topn_merge, node.keys, node.limit),
                    # both the running candidate set and the local
                    # top-N die at the merge: the chained per-page
                    # merges reuse one HBM allocation in place
                    donate_argnums=(0, 1),
                )
                running = merge_fn(running, local)
            if running is not None:
                yield running
            return
        if isinstance(node, P.Sort):
            pages = list(self.pages(node.source))
            if not pages:
                return
            merged = concat_all(pages)
            self._account_page(merged)
            key = ("sort", node.keys, None, merged.capacity)
            fn = self._jit(
                key, functools.partial(sort_page, sort_keys=node.keys)
            )
            yield fn(merged)
            return
        if isinstance(node, P.Limit):
            # running row count stays a DEVICE scalar (deferred-sync rule:
            # a host read here would poison every later launch); no early
            # exit, but every page is a cheap mask update
            consumed = jnp.int64(0)
            fn = self._jit(
                ("limit", node.count, node.offset),
                functools.partial(_limit_with_count, node.count,
                                  node.offset),
            )
            for page in self.pages(node.source):
                out, consumed = fn(page, consumed)
                yield out
            return
        if isinstance(node, P.Output):
            yield from self.pages(node.source)
            return
        if isinstance(node, P.Exchange):
            # single-device execution: every exchange is a no-op pass-
            # through (one device holds everything); DistExecutor overrides
            # with the collective implementations
            yield from self.pages(node.source)
            return
        raise TypeError(f"unknown node: {node!r}")

    def execute(self, node: P.PhysicalNode):
        """Materialize results: (column_names, list of row tuples).

        Reference analog: testing/MaterializedResult via LocalQueryRunner.

        Runs the whole plan with no host synchronization (see __init__),
        then checks the accumulated capacity-overflow flags once; on
        overflow the query re-runs with 4x capacities (query-scope analog
        of the reference's per-operator retry).
        """
        names = (
            list(node.names) if isinstance(node, P.Output) else None
        )
        self._capacity_boost = 1  # per-query; grows only across retries
        self.capacity_boost_retries = 0
        self.profile_store_hits = 0
        if self.trace is None:
            # untraced queries pin the span counter at 0; traced ones
            # reset at obs.attach (the DCN coordinator's stage spans
            # precede this root-fragment execute and must survive it)
            self.trace_spans = 0
        prof_key = self._seed_profile(node)
        self.peak_memory_bytes = 0
        self.spill_partitions_used = 0
        self.host_spill_pages = 0
        self.host_spill_bytes_used = 0
        self.disk_spill_pages = 0
        self.skew_chunks_used = 0
        self.device_oom_retries = 0
        self._oom_divisor = 1
        # generated/pallas counters accumulate for the executor's
        # lifetime (tests assert before/after deltas); snapshot them so
        # EXPLAIN ANALYZE can report THIS query's engagement
        self._joins_counter_base = (
            self.generated_joins_used, self.pallas_joins_used
        )
        cc_base = CC.snapshot()
        oom_left = self.device_oom_attempts
        # pre-compile plan verification (exec/plan_check.py): schema-
        # consistent edges, ladder/fault-line capacities, canonical
        # jit-key material — auto-on under pytest and bench --prewarm,
        # off on the hot serving path (plan_check session property)
        self._verify_plan(node)
        # result-cache points (presto_tpu/cache/): pages() serves the
        # selected subtrees from the shared store; a whole-plan hit
        # replays with zero compiles and zero launches
        self._select_cache_points(node)
        # transfer plane (ISSUE 12, exec/xfer.py): fresh per-query
        # gauges, and the host-serve sink — pages of the root (and of
        # anything under its Output pass-through chain) feed ONLY row
        # decode, so a cache replay there serves host pages with zero
        # crossings
        self._reset_transfer_gauges()
        self._host_sink_ids = self._sink_chain_ids(node)
        # lifecycle tracing (obs/trace.py): spans record at attempt/
        # page boundaries on the driver thread only — one `is None`
        # check is the entire cost with tracing off. Tracing borrows
        # the EXPLAIN ANALYZE per-node accounting for operator spans;
        # per-page cost is two perf_counter calls plus retaining one
        # deferred row-count scalar per (node, page) — no device sync
        # until after the run (the reference always collects
        # OperatorStats; execute() retains every output page anyway,
        # so the handles are marginal). query_trace_enabled=false
        # drops all of it for latency-critical serving.
        tr = self.trace
        own_stats = False
        if tr is not None and self._collect_stats is None:
            self._collect_stats = {}
            own_stats = True
        exec_span = None
        if tr is not None:
            exec_span = tr.begin("execute", type(node).__name__)
            self.trace_spans += 1
        _prev_sink = XF.swap_sink(self)
        try:
            attempts = 0
            while attempts < 6:
                self._begin_attempt()
                if self._collect_stats is not None:
                    # drop failed-attempt stats
                    self._collect_stats.clear()
                att_span = None
                if tr is not None:
                    att_span = tr.begin(
                        "attempt", f"a{attempts}", parent=exec_span,
                        boost=self._capacity_boost)
                    self.trace_spans += 1
                try:
                    self._maybe_inject_oom()
                    out_pages = []
                    for page in self.pages(node):
                        self._check_deadline()
                        out_pages.append(page)
                    overflow = self._overflow_flagged()
                    rows: List[tuple] = []
                    if not overflow:
                        for page in out_pages:
                            rows.extend(_decode_result_page(page))
                except QueryDeadlineExceeded:
                    if tr is not None:
                        tr.end(att_span, outcome="deadline")
                    raise
                except Exception as e:  # noqa: BLE001 - ladder gate
                    # device-OOM degradation: a RESOURCE_EXHAUSTED /
                    # allocation fault re-enters under a HALVED budget
                    # — an HBM-model miss becomes a slow correct query
                    # instead of a crashed one. Anything else (and an
                    # exhausted OOM budget) raises through.
                    if tr is not None:
                        tr.end(att_span, outcome="device-fault")
                    oom_left = self._absorb_device_fault(e, oom_left)
                    continue
                if overflow:
                    # re-enter at the next rung of the SHARED ladder
                    # (shapes.py): boosted sizes coincide with a larger
                    # query's first-attempt shapes, so the retry reuses
                    # cached programs instead of minting fresh ones
                    if tr is not None:
                        tr.end(att_span, outcome="overflow")
                    self._capacity_boost = SH.next_boost(
                        self._capacity_boost)
                    self.capacity_boost_retries += 1
                    attempts += 1
                    continue
                if tr is not None:
                    self._trace_operators(tr, att_span)
                    tr.end(att_span, outcome="ok", rows=len(rows))
                # overflow-free attempt: completed cache streams are
                # safe to publish (decode above already paid the sync)
                self._publish_cache_pending()
                if prof_key is not None:
                    self._record_profile(prof_key, len(rows))
                return names, rows
            raise RuntimeError(
                "capacity overflow persisted after 6 boosted retries"
            )
        finally:
            XF.swap_sink(_prev_sink)
            # release materialized intermediates (HBM/host pages) the
            # moment the query is done
            self._release_stream_cache()
            self._cache_points = {}
            self._cache_pending = []
            self._snap_compile_counters(cc_base)
            if tr is not None:
                tr.end(exec_span, boost=self._capacity_boost)
            if own_stats:
                self._collect_stats = None

    def _begin_attempt(self) -> None:
        """Per-attempt reset shared by every overflow-ladder driver
        (execute(), stream_fragment()): deferred flags, materialized
        intermediates (cached pages may embed overflow-truncated
        results), and the per-attempt gather/fusion counters — a
        retried attempt re-defers and re-materializes from scratch, so
        cumulative counts would break the exactly-one-gather-per-
        carried-column accounting. Unpublished result-cache streams
        drop too: they may embed the overflow that forced this retry."""
        self._pending_overflow = []
        self._release_stream_cache()
        self._cache_pending = []
        self._cache_inflight = set()
        self.gathers_deferred = 0
        self.gathers_materialized = 0
        self.fused_partial_aggs = 0
        self.program_launches = 0
        self.splits_scanned = 0
        self.queries_per_launch = 0
        self.memory_chunked_pipelines = 0
        self.buffers_donated = 0

    # -------------------------------------------------- result cache
    def _select_cache_points(self, node: P.PhysicalNode) -> None:
        """Per-query cache-point selection (cache/rules.py): maximal
        cacheable subtrees containing a materializing operator,
        gated by _cache_subtree_ok — the distributed executor allows
        only REPLICATED subtrees (mesh-sharded mid-plan pages cannot
        host-replay; replicated interiors can, the ISSUE 15 mesh
        residency rule).

        Keys are salted with the EXECUTOR config that can change a
        successful subtree's output without appearing in the plan:
        collect_k bounds collect-state aggregates (array_agg & family)
        and page_rows shapes the replayed page stream itself — the
        store is process-shared, so two sessions with different
        settings must never address one entry."""
        self._cache_points = {}
        if self.result_cache is None:
            return
        from presto_tpu.cache import select_cache_points

        from presto_tpu.cache.rules import stream_watermark

        salt = f"k{self.collect_k}.p{self.page_rows}"
        self._cache_points = {
            i: (f"{key}:{salt}", n, tables,
                stream_watermark(tables, self.catalogs),
                snap,
                # family keys carry the same executor salt as entry
                # keys: siblings under different collect_k/page_rows
                # must never answer each other
                (f"{fam[0]}:{salt}", fam[1])
                if fam is not None else None)
            for i, (key, n, tables, snap, fam) in select_cache_points(
                node, self.catalogs,
                allow=self._cache_subtree_ok,
                subsumable=self.cache_subsumption,
            ).items()
        }

    def count_warm_load(self, loaded: int, drops: int) -> None:
        """Fold one warm-start pass's outcome onto this executor's
        counter surface (runner.apply_session drives the pass; the
        counters live here so EXPLAIN ANALYZE / /metrics render them
        through the one registry snapshot)."""
        self.cache_warm_loads += loaded
        self.cache_manifest_drops += drops

    def _cache_subtree_ok(self, node: P.PhysicalNode) -> bool:
        """Whether a subtree's page stream may become a cache point.
        The base executor's pages are always ordinary single-stream
        Pages — everything is allowed; the DistExecutor narrows to
        replicated subtrees (mesh-sharded pages cannot host-replay)."""
        return True

    def _cached_pages(self, node: P.PhysicalNode,
                      entry) -> Iterator[Page]:
        """Serve one cache point: replay stored host pages on a hit
        (no compile, no launch, one device_put per page); on a miss,
        stream the real subtree (re-entrant through pages() via the
        inflight guard) while collecting, and stage the completed
        stream for publication after the attempt proves overflow-free.
        An abandoned stream (downstream Limit stopped consuming) never
        reaches the staging append, so partial page sets cannot be
        published."""
        key, _node_ref, tables, watermark, snap, family = entry
        tr = self.trace
        t0 = tr.now() if tr is not None else 0.0
        host_pages = self.result_cache.get_pages(key)
        label = type(node).__name__
        if host_pages is not None:
            self.result_cache_hits += 1
            # replayed pages still pass the per-query accounting: the
            # memory limit holds whether a page came off the device or
            # out of the cache, and EXPLAIN ANALYZE shows the replay's
            # pages/rows on this node (its subtree honestly shows
            # nothing — nothing executed; the Counters line carries
            # the result_cache_hits that explain why)
            st = None
            if self._collect_stats is not None:
                st = self._collect_stats.setdefault(
                    id(node), NodeStats(label))
            # the first redundant crossing the transfer auditor
            # surfaced (ISSUE 12 satellite): a hit whose pages feed
            # only statement serialization used to device_put every
            # host page and then pull it straight back at decode —
            # the host sink serves the stored pages as-is instead
            # (h2d_bytes == d2h_bytes == 0 on such a replay,
            # counter-pinned in tests/test_result_cache.py)
            serve_host = id(node) in self._host_sink_ids
            for hp in host_pages:
                dp = hp if serve_host else self._stage_replay(hp)
                self._account_page(dp)
                if st is not None:
                    st.pages += 1
                    st.row_counts.append(
                        int(XF.np_host(dp.valid).sum())
                        if serve_host else dp.num_rows())
                yield dp
            if tr is not None:
                tr.complete("cache", f"hit:{label}", t0, tr.now(),
                            pages=len(host_pages), key=key)
                self.trace_spans += 1
            return
        if family is not None:
            # subsumption rewrite (ISSUE 19): a cached SIBLING whose
            # filter descriptor CONTAINS this one answers by replaying
            # its (wider) pages through this node's own predicate — a
            # residual re-filter over cached pages instead of a rescan
            sib = self.result_cache.probe_family(family[0], family[1])
            wider = (self.result_cache.get_pages(sib[0])
                     if sib is not None else None)
            if wider is not None:
                self.result_cache_hits += 1
                self.cache_subsumed_hits += 1
                self.result_cache.count_subsumed()
                if tr is not None:
                    tr.complete("cache", f"subsume:{label}", t0,
                                tr.now(), key=key, wider=sib[0])
                    self.trace_spans += 1
                # stitch the wider pages UNDER this Filter via the
                # RemoteSource supplier path (the same ingest the
                # exchange plane replays through), then run the node's
                # own predicate over them — the residual filter
                skey = f"subsume:{id(node)}"
                rs = P.RemoteSource(
                    types=tuple(self.output_types(node.source)),
                    key=skey, origin=node.source,
                )
                synthetic = dataclasses.replace(node, source=rs)
                self.remote_sources[skey] = (
                    lambda pages=wider: iter(pages))
                collected: List = []
                try:
                    for page in self.pages(synthetic):
                        collected.append(page)
                        yield page
                finally:
                    self.remote_sources.pop(skey, None)
                # the narrow result publishes under its EXACT key, so
                # the next identical query hits without the rewrite
                self._cache_pending.append(
                    (key, collected, tables, watermark, snap, family))
                return
        self.result_cache_misses += 1
        if tr is not None:
            tr.complete("cache", f"miss:{label}", t0, tr.now(),
                        key=key)
            self.trace_spans += 1
        self._cache_inflight.add(id(node))
        try:
            collected = []
            for page in self.pages(node):
                collected.append(page)
                yield page
        finally:
            self._cache_inflight.discard(id(node))
        self._cache_pending.append(
            (key, collected, tables, watermark, snap, family))

    def _stage_replay(self, page: Page) -> Page:
        """Re-stage one replayed host page for a DEVICE consumer —
        overridable so the DistExecutor can commit replays as
        properly mesh-replicated arrays instead of device-0 pages."""
        return XF.to_device(page, label="cache-replay")

    def _publish_cache_pending(self) -> None:
        """Publish the attempt's completed cache streams — called by
        the drivers exactly once per SUCCESSFUL (overflow-free)
        attempt, which is also where the engine syncs anyway, so the
        store's per-page D2H reads stay off the deferred-sync hot
        path."""
        pending, self._cache_pending = self._cache_pending, []
        cache = self.result_cache
        if cache is None:
            return
        for key, pages, tables, watermark, snap, family in pending:
            self.result_cache_evictions += cache.put_pages(
                key, pages, tables, watermark=watermark,
                snap=snap, family=family,
            )

    def _overflow_flagged(self) -> bool:
        """OR-reduce the attempt's deferred overflow flags — the ONE
        host sync of the deferred-sync discipline (see __init__)."""
        if not self._pending_overflow:
            return False
        flag = self._pending_overflow[0]
        for f in self._pending_overflow[1:]:
            flag = flag | f
        return bool(flag)

    def stream_fragment(self, node: P.PhysicalNode, emit,
                        cancelled=lambda: False,
                        on_attempt=None) -> List:
        """Stream a plan fragment's pages through ``emit`` under the
        SAME query-scope overflow ladder as execute() — for drivers
        that ship results incrementally (server/worker.py's task
        runtime) instead of materializing rows. Returns the emit()
        results of the last (overflow-free) attempt; a truncated page
        set can never escape because results publish only per
        completed attempt. ``on_attempt`` (optional) is called at the
        start of EVERY attempt — drivers whose emit writes to
        external, tiered storage (the spooled-exchange buffers) reset
        it there so a boosted retry never double-publishes. Raises
        after 6 boosted retries."""
        self._capacity_boost = 1
        self.capacity_boost_retries = 0
        self.profile_store_hits = 0
        if self.trace is None:
            self.trace_spans = 0
        # profile seeding mirrors execute(): a repeated fragment shape
        # starts at its settled capacity bucket on the worker too
        prof_key = self._seed_profile(node)
        self.device_oom_retries = 0
        self._oom_divisor = 1
        cc_base = CC.snapshot()
        oom_left = self.device_oom_attempts
        # same pre-compile verification as execute(): a shipped
        # fragment is a plan tree too (worker-side task runtime)
        self._verify_plan(node)
        # and the same result-cache point selection: a repeated leaf
        # fragment replays on the worker too (split identity rides in
        # the SplitFilterConnector's snapshot token, so two tasks of
        # one fragment on different shares can never share a key)
        self._select_cache_points(node)
        # transfer plane: fragment pages feed emit() (host
        # serialization) directly, so the fragment root chain is the
        # host-serve sink — a worker-side cache replay never re-stages
        self._reset_transfer_gauges()
        self._host_sink_ids = self._sink_chain_ids(node)
        _prev_sink = XF.swap_sink(self)
        tr = self.trace
        try:
            attempts = 0
            while attempts < 6:
                self._begin_attempt()
                if on_attempt is not None:
                    on_attempt()
                att_span = None
                if tr is not None:
                    att_span = tr.begin("attempt", f"a{attempts}",
                                        boost=self._capacity_boost)
                    self.trace_spans += 1
                try:
                    self._maybe_inject_oom()
                    out: List = []
                    for page in self.pages(node):
                        if cancelled():
                            if tr is not None:
                                tr.end(att_span, outcome="cancelled")
                            return out
                        self._check_deadline()
                        out.append(emit(page))
                except QueryDeadlineExceeded:
                    if tr is not None:
                        tr.end(att_span, outcome="deadline")
                    raise
                except Exception as e:  # noqa: BLE001 - ladder gate
                    # same device-OOM degradation as execute(): retry
                    # under a halved budget so the worker's fragment
                    # degrades to chunked execution instead of failing
                    # the task (the coordinator's long-poll tolerates
                    # the delay)
                    if tr is not None:
                        tr.end(att_span, outcome="device-fault")
                    oom_left = self._absorb_device_fault(e, oom_left)
                    continue
                if not self._overflow_flagged():
                    if tr is not None:
                        tr.end(att_span, outcome="ok", pages=len(out))
                    # publication mirrors the emit discipline: only a
                    # completed overflow-free attempt's streams cache
                    self._publish_cache_pending()
                    if prof_key is not None:
                        self._record_profile(prof_key, None,
                                             pages_out=len(out))
                    return out
                # same shared-ladder re-entry as execute(): fragment
                # retries land on rungs the cache already paid for
                if tr is not None:
                    tr.end(att_span, outcome="overflow")
                self._capacity_boost = SH.next_boost(self._capacity_boost)
                self.capacity_boost_retries += 1
                attempts += 1
            raise RuntimeError(
                "fragment capacity overflow persisted after 6 boosted "
                "retries"
            )
        finally:
            XF.swap_sink(_prev_sink)
            # close materialized intermediates (incl. disk-tier spill
            # dirs) the moment the fragment is done — never rely on
            # __del__ timing (same discipline as execute())
            self._release_stream_cache()
            self._cache_points = {}
            self._cache_pending = []
            self._snap_compile_counters(cc_base)

    def _snap_compile_counters(self, base) -> None:
        """Record this query's compile-cost delta (see compilecache.py;
        process-wide counters, so concurrent queries share attribution)."""
        d = CC.delta(base)
        self.programs_compiled = d["programs_compiled"]
        self.program_cache_hits = d["program_cache_hits"]
        self.compile_wall_s = d["compile_wall_s"]

    def _release_stream_cache(self) -> None:
        """Invalidate materialized intermediates, CLOSING each PageStore
        explicitly (disk-tier stores hold presto_tpu_spill_* temp dirs
        whose cleanup must not rely on __del__ timing)."""
        for store in self._stream_cache.values():
            try:
                store.close()
            except Exception:  # noqa: BLE001 - best-effort close; a
                pass           # failed spill-dir sweep must not mask
                # the query's own result/error path
        self._stream_cache = {}

    def _account_page(self, page: Page) -> None:
        size = page_bytes(page)
        # streaming model: at most a handful of pages per operator are
        # live at once; the high-water proxy is the largest single page
        # times the plan's pipeline depth, tracked coarsely as a running
        # peak of per-page footprints
        self.peak_memory_bytes = max(self.peak_memory_bytes, size)
        if (
            self.max_memory_bytes is not None
            and size > self.max_memory_bytes
        ):
            raise MemoryBudgetExceeded(
                f"page footprint {size} bytes exceeds query memory limit "
                f"{self.max_memory_bytes} (reference: "
                f"ExceededMemoryLimitException)"
            )

    def execute_with_stats(self, node: P.PhysicalNode):
        """EXPLAIN ANALYZE support: run the query collecting per-node
        wall time / page count / output rows. Row counts stay device-side
        during the run and resolve here (one sync at the end)."""
        self._collect_stats = {}
        try:
            names, rows = self.execute(node)
            stats = dict(self._collect_stats)
        finally:
            self._collect_stats = None
        # query-level execution counters ride under a string key (node
        # entries key by id(node), an int — no collision); PlanPrinter
        # renders them as a trailing Counters line. The gather/fusion
        # counters are per-attempt (reset in _begin_attempt, so they
        # describe the successful attempt); the lifetime-cumulative
        # join counters report as THIS query's delta over the snapshot
        # execute() took.
        base_gen, base_pal = getattr(self, "_joins_counter_base", (0, 0))
        # registry-driven (exec/counters.py): every declared counter
        # surfaces here — and therefore in EXPLAIN ANALYZE text and
        # analyze_rung, which render all keys — with no per-counter
        # hand wiring. The lifetime-cumulative join counters override
        # to THIS query's delta over the snapshot execute() took.
        ctr = CTRS.snapshot(self)
        ctr["generated_joins_used"] = self.generated_joins_used - base_gen
        ctr["pallas_joins_used"] = self.pallas_joins_used - base_pal
        # computed entries (counters.COMPUTED_COUNTERS):
        # splits_per_launch > 1 means the per-split driver loop folded
        # into XLA (ROOFLINE §7); peak_device_bytes is the attempt's
        # largest single device buffer (membudget.py); warmed runs
        # report programs_compiled=0 with the wall under compile_wall_s
        ctr["splits_per_launch"] = (
            round(self.splits_scanned / self.program_launches, 1)
            if self.program_launches else 0.0
        )
        ctr["compile_wall_s"] = self.compile_wall_s
        # transfer ledger (ISSUE 12, exec/xfer.py): the float wall of
        # this query's metered host<->device crossings; the byte/count
        # gauges ride in the registry snapshot above
        ctr["transfer_wall_s"] = round(self.transfer_wall_s, 6)
        ctr["peak_device_bytes"] = self.peak_memory_bytes
        ctr["deadline_ms_remaining"] = (
            int((self.query_deadline - time.monotonic()) * 1000)
            if self.query_deadline is not None else -1
        )
        stats["counters"] = ctr
        return names, rows, stats

    # -------------------------------------------------------- aggregation
    def _agg_in_types(self, node: P.Aggregation) -> List[Optional[T.SqlType]]:
        src = self.output_types(node.source)
        return [
            None if s.channel is None else src[s.channel]
            for s in node.aggregates
        ]

    def _partial_origin(self, node: P.Aggregation) -> P.Aggregation:
        """The partial-step aggregation feeding a final-step one (possibly
        through exchanges or a DCN RemoteSource); needed to recover
        original input types."""
        src = node.source
        while isinstance(src, P.Exchange):
            src = src.source
        if isinstance(src, P.RemoteSource) and src.origin is not None:
            src = src.origin
        if not (isinstance(src, P.Aggregation) and src.step == "partial"):
            raise TypeError(
                "final-step aggregation must consume a partial-step one"
            )
        return src

    @property
    def _collect_k_eff(self) -> int:
        """Collect-state slots per group for this attempt: the session
        bound scaled by the overflow-retry boost, so a group exceeding
        array_agg_max_elements lands on the same boosted-retry ladder
        as every other capacity (SURVEY §8.2.1)."""
        return self.collect_k * self._capacity_boost

    def _agg_extra_types(self, node: P.Aggregation):
        """Per-aggregate extra input types (map_agg's value column),
        resolved against the aggregation's source schema."""
        src = self.output_types(node.source)
        return tuple(
            tuple(src[c] for c in spec.extra_channels)
            for spec in node.aggregates
        )

    def _exec_agg_partial(self, node: P.Aggregation) -> Iterator[Page]:
        """Partial step only: one state page per input page (reference:
        AggregationNode.Step.PARTIAL before the exchange). When the
        source is a fusable scan chain, the WHOLE pipeline — generation
        through partial aggregation — compiles to one program per split
        (this is the path shipped-plan worker fragments execute)."""
        in_types = self._agg_in_types(node)
        layouts = [
            S.state_layout(s.function, t)
            for s, t in zip(node.aggregates, in_types)
        ]
        pcap = _next_pow2(node.capacity * self._capacity_boost)
        tail = self._fused_partial_tail(
            node, layouts, pcap, 64 * self._capacity_boost,
        )
        if tail is not None:
            fused = self._fused_stream(
                node.source, agg_tail=tail,
                key_extra=("partial", node.group_channels,
                           node.aggregates, pcap,
                           64 * self._capacity_boost,
                           self._collect_k_eff,
                           self._pallas_agg_on()),
            )
            if fused is not None:
                yield from fused
                return
        if not node.group_channels:
            fn = self._jit(
                ("gagg_partial", node.aggregates,
                 tuple(tuple(l) for l in layouts)),
                functools.partial(
                    _partial_global_agg, node.aggregates,
                    tuple(tuple(l) for l in layouts)
                ),
            )
            for page in self.pages(node.source):
                yield fn(page)
            return
        cap = _next_pow2(node.capacity * self._capacity_boost)
        max_iters = 64 * self._capacity_boost
        pallas_agg = self._pallas_agg_on()
        if pallas_agg:
            self.pallas_kernels_used += 1
        fn = self._jit(
            ("agg_partial", node.group_channels, node.aggregates,
             tuple(tuple(l) for l in layouts), self._collect_k_eff,
             pallas_agg),
            functools.partial(
                _partial_agg_page, node.group_channels, node.aggregates,
                tuple(tuple(l) for l in layouts),
                collect_k=self._collect_k_eff, pallas_agg=pallas_agg,
            ),
            static_argnums=(1, 2),
        )
        for page in self.pages(node.source):
            out, overflow = fn(
                page, min(cap, _next_pow2(page.capacity)), max_iters
            )
            self._pending_overflow.append(overflow)
            yield out

    def _exec_agg_final(self, node: P.Aggregation) -> Iterator[Page]:
        """Final step: merge partial-state pages after an exchange."""
        origin = self._partial_origin(node)
        in_types = self._agg_in_types(origin)
        layouts = [
            S.state_layout(s.function, t)
            for s, t in zip(node.aggregates, in_types)
        ]
        pages = list(self.pages(node.source))
        if not node.group_channels:
            merged = (
                concat_all(pages) if pages
                else _empty_state_page(node.aggregates, layouts,
                                      collect_k=self._collect_k_eff)
            )
            fn = self._jit(
                ("gagg_final", node.aggregates,
                 tuple(tuple(l) for l in layouts), tuple(in_types)),
                functools.partial(
                    _final_global_agg, node.aggregates,
                    tuple(tuple(l) for l in layouts), tuple(in_types)
                ),
            )
            yield fn(merged)
            return
        if not pages:
            return
        merged = concat_all(pages) if len(pages) > 1 else pages[0]
        fn = self._jit(
            ("agg_final", node.group_channels, node.aggregates,
             tuple(tuple(l) for l in layouts), tuple(in_types),
             self._agg_extra_types(origin), self._collect_k_eff),
            functools.partial(
                _final_agg_page, node.group_channels, node.aggregates,
                tuple(tuple(l) for l in layouts), tuple(in_types),
                collect_k=self._collect_k_eff,
                extra_types=self._agg_extra_types(origin),
            ),
            static_argnums=(1, 2),
        )
        fcap = min(
            _next_pow2(node.capacity * self._capacity_boost),
            _next_pow2(merged.capacity),
        )
        out, overflow = fn(merged, fcap, 64 * self._capacity_boost)
        self._pending_overflow.append(overflow)
        yield out

    # ------------------------------------------------ IVM kernel plane
    def ivm_delta_states(self, partial_node: P.Aggregation) -> List:
        """Run a view's partial-step aggregation over the delta
        window (the executor's catalogs hold the pinned
        StreamWindowConnector) and return HOST copies of its
        partial-state pages — the O(new rows) half of an incremental
        view refresh (streaming/ivm.py). Rides stream_fragment's
        overflow ladder, the fused scan→partial-agg path where the
        chain fuses, and the same canonical jit-cache entries a cold
        single-step run compiles."""
        return self.stream_fragment(
            partial_node,
            emit=lambda p: XF.to_host(p, label="ivm-delta"),
        )

    def ivm_fold_finalize(self, node: P.Aggregation, state_pages,
                          cap_hint: Optional[int] = None):
        """Merge partial-state pages (host pytrees: the persisted
        settled state plus this refresh's delta states) into ONE
        settled partial state and finalize it — the other half of an
        IVM refresh. Reuses the exact agg_merge / agg_final kernels
        (and canonical jit keys) the single-step aggregation path
        compiles, under a local boost ladder: a state overflow re-
        stages and retries at the next shapes.py rung, same escape as
        every other capacity decision. Returns
        ``(settled_host_state_page, final_host_page)`` — the settled
        state is pulled to host BEFORE finalization because the
        final-step program donates its input buffer on TPU.

        ``cap_hint`` (the view's OBSERVED group cardinality from its
        last finalize) sizes the settled state tightly: the planner's
        capacity estimate derives from the LOG's row count and would
        pin an ever-growing state page to O(log) slots — the refresh
        must stay O(delta) + O(groups), so the state compacts to the
        observed cardinality and true growth overflows onto the boost
        ladder like every other capacity decision."""
        if not state_pages:
            raise ValueError("ivm_fold_finalize needs >=1 state page")
        in_types = self._agg_in_types(node)
        layouts = [
            S.state_layout(s.function, t)
            for s, t in zip(node.aggregates, in_types)
        ]
        layouts_t = tuple(tuple(l) for l in layouts)
        nkeys = len(node.group_channels)
        boost = 1
        for _ in range(6):
            max_iters = 64 * boost
            collect_k = self.collect_k * boost
            merge_fn = self._jit(
                ("agg_merge", node.aggregates, layouts_t, nkeys,
                 collect_k),
                functools.partial(
                    _merge_partials_page, node.aggregates, layouts_t,
                    nkeys, collect_k=collect_k,
                ),
                static_argnums=(1, 2),
                donate_argnums=(0,),
            )
            final_fn = self._jit(
                ("agg_final", node.group_channels, node.aggregates,
                 layouts_t, tuple(in_types),
                 self._agg_extra_types(node), collect_k),
                functools.partial(
                    _final_agg_page, node.group_channels,
                    node.aggregates, layouts_t, tuple(in_types),
                    collect_k=collect_k,
                    extra_types=self._agg_extra_types(node),
                ),
                static_argnums=(1, 2),
                donate_argnums=(0,),
            )
            # re-stage per attempt: the merge program donates its
            # concat input, so a boosted retry must rebuild it
            staged = [XF.to_device(p, label="ivm-state")
                      for p in state_pages]
            merged = (concat_all(staged) if len(staged) > 1
                      else staged[0])
            self._account_page(merged)
            base = (cap_hint if cap_hint else node.capacity)
            cap = _next_pow2(max(base, 8) * boost)
            mcap = min(cap, _next_pow2(merged.capacity))
            settled, ovf = merge_fn(merged, mcap, max_iters)
            if bool(ovf):
                boost = SH.next_boost(boost)
                continue
            # host copy FIRST: final_fn donates the settled buffer
            settled_host = XF.to_host(settled, label="ivm-state")
            fcap = min(cap, _next_pow2(settled.capacity))
            final, ovf = final_fn(settled, fcap, max_iters)
            if bool(ovf):
                boost = SH.next_boost(boost)
                continue
            return settled_host, XF.to_host(final, label="ivm-final")
        raise RuntimeError(
            "IVM state fold overflow persisted after 6 boosted retries"
        )

    def _exec_aggregation(self, node: P.Aggregation) -> Iterator[Page]:
        if node.step == "partial":
            yield from self._exec_agg_partial(node)
            return
        if node.step == "final":
            yield from self._exec_agg_final(node)
            return
        in_types = self._agg_in_types(node)
        layouts = [
            S.state_layout(s.function, t)
            for s, t in zip(node.aggregates, in_types)
        ]
        if not node.group_channels:
            yield self._exec_global_agg(node, in_types, layouts)
            return

        parts = 1
        src_types = self.output_types(node.source)
        can_partition = self._keys_partitionable(
            src_types, node.group_channels
        )
        if can_partition:
            est_rows = self.estimate_rows(node.source)
            # boost-scaled: a fold-overflow retry (true cardinality
            # past the planner estimate AND the governed fold cap,
            # which is pinned under the fault line and cannot grow)
            # must eventually cross INTO the partitioned path — the
            # single path's only remaining escape
            cap_est = _next_pow2(
                max(node.capacity, 8) * self._capacity_boost
            )
            n_pages = max(-(-est_rows // max(self.page_rows, 1)), 1)
            state_types = [src_types[c] for c in node.group_channels]
            for spec, in_t in zip(node.aggregates, in_types):
                state_types.extend(
                    st.type for st in S.state_layout(spec.function, in_t)
                )
            merged_slots = min(est_rows, n_pages * cap_est)
            if self._capacity_boost > 1:
                # a boosted retry is EVIDENCE the estimates are low
                # (something overflowed at the previous capacities):
                # stop letting an under-estimated est_rows cap the
                # partition decision, or the boost ladder can climb
                # forever without the escape ever engaging
                merged_slots = max(merged_slots, cap_est)
            state_row_b = _row_bytes(state_types)
            if self.spill_bytes is not None:
                parts = self._spill_partitions(merged_slots * state_row_b)
            # governed (membudget.py): aggregation state must fit its
            # budget share regardless of the spill threshold — over
            # budget, the aggregation runs in hash-partition passes.
            # rows_cap = the single path's governed FOLD cap (fr>>2,
            # see fold_cap below), not the raw fault line: a state the
            # fold can never hold must partition, or boosted retries
            # would never converge
            budget = self._budget()
            fr = self._fault_rows()
            gparts = SH.parts_for(
                merged_slots, state_row_b,
                rows_cap=max(fr >> 2, 8192) if fr else None,
                bytes_cap=(budget // MB.BUILD_SHARE_DIV
                           if budget else None),
            )
            if gparts > parts:
                parts = gparts
                self.memory_chunked_pipelines += 1
        if parts > 1:
            yield from self._exec_agg_partitioned(
                node, parts, in_types, layouts
            )
            return

        # no global clamp: boosted retries must be able to grow past
        # page_rows (join-output pages can exceed it); the per-page
        # min(..., page.capacity) below bounds each launch
        cap = _next_pow2(node.capacity * self._capacity_boost)
        # optimistic clamp: the planner's capacity estimate has no
        # selectivity model and routinely over-estimates 100x (Q3's
        # 1.1M-orderkey estimate vs 11k real groups); every sort/
        # scatter in the grouped path scales with capacity, so start
        # tight — the boost ladder grows past it when real cardinality
        # overflows (same escape as every capacity decision here)
        if self.agg_optimistic_rows:
            cap = min(cap, _next_pow2(
                self.agg_optimistic_rows * self._capacity_boost))
        pallas_agg = self._pallas_agg_on()
        if pallas_agg:
            self.pallas_kernels_used += 1
        partial_fn = self._jit(
            ("agg_partial", node.group_channels, node.aggregates,
             tuple(tuple(l) for l in layouts), self._collect_k_eff,
             pallas_agg),
            functools.partial(
                _partial_agg_page, node.group_channels, node.aggregates,
                tuple(tuple(l) for l in layouts),
                collect_k=self._collect_k_eff, pallas_agg=pallas_agg,
            ),
            static_argnums=(1, 2),
        )
        # boosted retries also deepen the hash-probe iteration budget:
        # when cap is already clipped at the page capacity the only
        # remaining overflow source is unresolved probing after max_iters
        # lockstep rounds, which more capacity alone cannot fix
        max_iters = 64 * self._capacity_boost
        # Incremental fold: buffered partial pages merge into one
        # bounded state page instead of one giant concat — a 6-page
        # pipeline with a 2M capacity estimate otherwise concats 6M+
        # slots and crosses the >=4M-row axon fault line (and wastes
        # memory even where it doesn't fault). fold_cap deliberately
        # undersizes vs the planner estimate; true high-cardinality
        # group-bys overflow onto the boosted-retry ladder (and, when
        # spill is on, onto partitioned passes).
        fold_cap = min(cap, _next_pow2((1 << 20) * self._capacity_boost))
        fr = self._fault_rows()
        if fr and can_partition:
            # governed: acc + flush batch + one page stays under the
            # device fault line even at full boost — safe to PIN only
            # because true high-cardinality states have an escape (the
            # boost-scaled partitioned path above). Non-partitionable
            # keys (strings) have no such rewrite: they keep the
            # legacy boost-growing cap, same exposure as before the
            # governor, rather than a pin that can never converge
            fold_cap = min(fold_cap, max(fr >> 2, 8192))
        merge_fn = self._jit(
            ("agg_merge", node.aggregates,
             tuple(tuple(l) for l in layouts),
             len(node.group_channels), self._collect_k_eff),
            functools.partial(
                _merge_partials_page, node.aggregates,
                tuple(tuple(l) for l in layouts),
                len(node.group_channels),
                collect_k=self._collect_k_eff,
            ),
            static_argnums=(1, 2),
            # the fold accumulator concat is dead after the merge —
            # donation reuses its HBM for the merged state in place
            donate_argnums=(0,),
        )
        fold = _FoldBuffer(self, merge_fn, fold_cap, max_iters,
                           2 * fold_cap)
        # scan→filter→project→partial-agg as ONE program per split when
        # the source chain fuses (the fused stream's state pages feed
        # the same fold/final machinery)
        tail = self._fused_partial_tail(node, layouts, cap, max_iters)
        fused = (
            self._fused_stream(
                node.source, agg_tail=tail,
                key_extra=("single", node.group_channels,
                           node.aggregates, cap, max_iters,
                           self._collect_k_eff,
                           self._pallas_agg_on()),
            )
            if tail is not None and node.group_channels else None
        )
        if fused is not None:
            for out in fused:
                fold.add(out)
        else:
            for page in self._agg_source_pages(node):
                # distinct groups <= rows: clip the capacity to the page
                out, overflow = partial_fn(
                    page, min(cap, _next_pow2(page.capacity)), max_iters
                )
                self._pending_overflow.append(overflow)
                fold.add(out)
        merged = fold.final_merged()
        if merged is None:
            return
        final_fn = self._jit(
            ("agg_final", node.group_channels, node.aggregates,
             tuple(tuple(l) for l in layouts), tuple(in_types),
             self._agg_extra_types(node), self._collect_k_eff),
            functools.partial(
                _final_agg_page, node.group_channels, node.aggregates,
                tuple(tuple(l) for l in layouts), tuple(in_types),
                collect_k=self._collect_k_eff,
                extra_types=self._agg_extra_types(node),
            ),
            static_argnums=(1, 2),
            # the fold's settled state page dies at the final merge —
            # the fold chain and the finisher share one HBM allocation
            donate_argnums=(0,),
        )
        fcap = min(
            _next_pow2(node.capacity * self._capacity_boost),
            _next_pow2(merged.capacity),
        )
        out, overflow = final_fn(merged, fcap, max_iters)
        self._pending_overflow.append(overflow)
        yield out

    def _agg_source_pages(self, node: P.Aggregation) -> Iterator[Page]:
        """Aggregation input stream, densified through a rolling
        compaction buffer when the source subtree contains a join: join
        output pages keep probe capacity but are usually mostly-invalid
        (build filters + match rate), and every sort/scatter in the
        blocking aggregation scales with SLOT count, not valid rows.
        Each input page merge-compacts into one accumulator page (a
        stable argsort + output-sized gathers — cheap), so the
        aggregation usually runs ONCE over one dense page instead of
        once per sparse page plus merges. Rows beyond the accumulator
        flag overflow and ride the boosted-retry ladder (reference
        analog: every Presto operator re-compacts via PageBuilder —
        pages are always dense there)."""
        if node.capacity <= A.MATMUL_AGG_MAX_GROUPS:
            # few groups: the aggregation runs on the dense/MXU paths
            # whose per-page cost is already near-free — and at scale
            # the accumulator could not hold a high-selectivity stream
            # anyway (e.g. Q5 SF100's ~18M qualifying rows vs a <=2M
            # buffer); stream straight through
            yield from self.pages(node.source)
            return
        yield from self._compacted_stream(node.source, node)

    def _compacted_stream(self, src: P.PhysicalNode,
                          key_node) -> Iterator[Page]:
        if not self.agg_compact or not _subtree_has_join(src):
            yield from self.pages(src)
            return
        # the planner's group-count estimate is a LOWER bound on the
        # stream's valid rows — an accumulator smaller than it is
        # guaranteed to overflow (observed: Q3 SF10's ~3M qualifying
        # rows vs the 262k optimistic default), so size C to cover the
        # estimate and skip compaction entirely when that can't fit
        # under the axon >=4M-row fault line (the partitioned/plain
        # paths handle dense streams without a rolling buffer)
        est = _next_pow2(max(getattr(key_node, "capacity", 8), 8))
        C = _next_pow2(
            max(self.agg_optimistic_rows or (1 << 18), est, 8192)
            * self._capacity_boost
        )
        if C > (1 << 21):
            yield from self.pages(src)
            return
        # bare kernels: ONE canonical entry each serves every stream
        first = self._jit(
            ("stream_compact1",), _compact_with_flag,
            static_argnums=(1,),
        )
        merge = self._jit(
            ("stream_compact2",), _merge_compact_flag,
            static_argnums=(2,),
        )
        acc = None
        for page in self.pages(src):
            if acc is None:
                acc, overflow = first(page, C)
            else:
                acc, overflow = merge(acc, page, C)
            self._pending_overflow.append(overflow)
        if acc is not None:
            yield acc

    def _exec_agg_partitioned(
        self, node: P.Aggregation, parts: int, in_types, layouts
    ) -> Iterator[Page]:
        """Partition-wise grouped aggregation (spill analog): group-key
        hash partitions keep per-partition state ~1/P of the one-shot
        size; partitions are disjoint so the union of outputs is exact.
        Two strategies (reference: SpillableHashAggregationBuilder's
        partition-and-merge):
          - parts <= 32: SINGLE source pass, P device-resident
            accumulators folded incrementally — the source (often an
            expensive join) executes once and every buffer stays small;
          - larger P: one pass per partition re-streaming the source
            (recomputation instead of spill files — generator scans are
            free, SURVEY §8.2.6) with O(1/P) working set."""
        if parts <= 32:
            # budget check: the fold path keeps ~3 buffers per partition
            # resident (~6x the capacity estimate in state rows); under
            # an explicit query memory budget that exceeds the point of
            # spilling — fall through to the O(1/P) multi-pass instead
            cap_est = _next_pow2(node.capacity * self._capacity_boost)
            pcap_est = _next_pow2(max(cap_est // parts * 2, 1024))
            src_types = self.output_types(node.source)
            state_types = [src_types[c] for c in node.group_channels]
            for layout in layouts:
                state_types.extend(st.type for st in layout)
            resident = 3 * parts * pcap_est * _row_bytes(state_types)
            if (
                self.max_memory_bytes is None
                or resident <= self.max_memory_bytes
            ):
                yield from self._exec_agg_partition_fold(
                    node, parts, in_types, layouts
                )
                return
        self.spill_partitions_used = max(self.spill_partitions_used, parts)
        pfilter = self._partition_filter(node.group_channels, parts)
        cap = _next_pow2(node.capacity * self._capacity_boost)
        pcap = SH.chunk_bucket(cap, parts)
        max_iters = 64 * self._capacity_boost
        pallas_agg = self._pallas_agg_on()
        if pallas_agg:
            self.pallas_kernels_used += 1
        partial_fn = self._jit(
            ("agg_partial", node.group_channels, node.aggregates,
             tuple(tuple(l) for l in layouts), self._collect_k_eff,
             pallas_agg),
            functools.partial(
                _partial_agg_page, node.group_channels, node.aggregates,
                tuple(tuple(l) for l in layouts),
                collect_k=self._collect_k_eff, pallas_agg=pallas_agg,
            ),
            static_argnums=(1, 2),
        )
        final_fn = self._jit(
            ("agg_final", node.group_channels, node.aggregates,
             tuple(tuple(l) for l in layouts), tuple(in_types),
             self._agg_extra_types(node), self._collect_k_eff),
            functools.partial(
                _final_agg_page, node.group_channels, node.aggregates,
                tuple(tuple(l) for l in layouts), tuple(in_types),
                collect_k=self._collect_k_eff,
                extra_types=self._agg_extra_types(node),
            ),
            static_argnums=(1, 2),
            donate_argnums=(0,),  # per-pass fold state dies here
        )
        nkeys = len(node.group_channels)
        merge_fn = self._jit(
            ("agg_merge", node.aggregates,
             tuple(tuple(l) for l in layouts),
             len(node.group_channels), self._collect_k_eff),
            functools.partial(
                _merge_partials_page, node.aggregates,
                tuple(tuple(l) for l in layouts), nkeys,
                collect_k=self._collect_k_eff,
            ),
            static_argnums=(1, 2),
            donate_argnums=(0,),  # fold concat dead after the merge
        )
        src_stream = self._source_stream(node.source)
        for p in range(parts):
            pj = jnp.uint64(p)
            # incremental fold: buffered partial pages merge into one
            # pcap-sized state page whenever they pile up, so per-pass
            # memory is O(pcap), not O(pages x pcap)
            fold = _FoldBuffer(self, merge_fn, pcap, max_iters, 4 * pcap)
            for page in src_stream():
                f = pfilter(page, pj)
                out, overflow = partial_fn(
                    f, min(pcap, _next_pow2(page.capacity)), max_iters
                )
                self._pending_overflow.append(overflow)
                fold.add(out)
            if not fold.saw_input:
                return
            merged = fold.final_merged()
            fcap = min(pcap, _next_pow2(merged.capacity))
            out, overflow = final_fn(merged, fcap, max_iters)
            self._pending_overflow.append(overflow)
            yield out

    def _exec_agg_partition_fold(
        self, node: P.Aggregation, parts: int, in_types, layouts
    ) -> Iterator[Page]:
        """Single-pass partitioned aggregation: every source page is
        partial-aggregated, split into P partitions by group-key hash
        over the PARTIAL page's key channels, compacted, and folded into
        per-partition accumulators. Memory is O(P * pcap) and every
        individual buffer stays ~3*pcap — small enough for the axon
        >=4M-row fault line — while the source streams exactly once
        (crucial when it is a join pipeline, not a free generator
        re-scan)."""
        self.spill_partitions_used = max(self.spill_partitions_used, parts)
        nkeys = len(node.group_channels)
        # partial output pages carry the keys at channels 0..nkeys-1
        pfilter = self._partition_filter(tuple(range(nkeys)), parts)
        cap = _next_pow2(node.capacity * self._capacity_boost)
        pcap = SH.chunk_bucket(cap, parts)
        max_iters = 64 * self._capacity_boost
        pallas_agg = self._pallas_agg_on()
        if pallas_agg:
            self.pallas_kernels_used += 1
        partial_fn = self._jit(
            ("agg_partial", node.group_channels, node.aggregates,
             tuple(tuple(l) for l in layouts), self._collect_k_eff,
             pallas_agg),
            functools.partial(
                _partial_agg_page, node.group_channels, node.aggregates,
                tuple(tuple(l) for l in layouts),
                collect_k=self._collect_k_eff, pallas_agg=pallas_agg,
            ),
            static_argnums=(1, 2),
        )
        merge_fn = self._jit(
            ("agg_merge", node.aggregates,
             tuple(tuple(l) for l in layouts),
             len(node.group_channels), self._collect_k_eff),
            functools.partial(
                _merge_partials_page, node.aggregates,
                tuple(tuple(l) for l in layouts), nkeys,
                collect_k=self._collect_k_eff,
            ),
            static_argnums=(1, 2),
            donate_argnums=(0,),  # fold concat dead after the merge
        )
        final_fn = self._jit(
            ("agg_final", node.group_channels, node.aggregates,
             tuple(tuple(l) for l in layouts), tuple(in_types),
             self._agg_extra_types(node), self._collect_k_eff),
            functools.partial(
                _final_agg_page, node.group_channels, node.aggregates,
                tuple(tuple(l) for l in layouts), tuple(in_types),
                collect_k=self._collect_k_eff,
                extra_types=self._agg_extra_types(node),
            ),
            static_argnums=(1, 2),
            donate_argnums=(0,),  # per-partition fold state dies here
        )

        folds = [
            _FoldBuffer(self, merge_fn, pcap, max_iters, 2 * pcap)
            for _ in range(parts)
        ]
        for page in self._agg_source_pages(node):
            out, overflow = partial_fn(
                page, min(cap, _next_pow2(page.capacity)), max_iters
            )
            self._pending_overflow.append(overflow)
            piece_cap = min(
                _next_pow2(
                    max(out.capacity // parts * 2, 256)
                    * self._capacity_boost
                ),
                _next_pow2(out.capacity),
            )
            for p in range(parts):
                f = pfilter(out, jnp.uint64(p))
                self._pending_overflow.append(f.num_rows() > piece_cap)
                folds[p].add(compact_page(f, piece_cap))
        for fold in folds:
            merged = fold.final_merged()
            if merged is None:
                continue
            fcap = min(pcap, _next_pow2(merged.capacity))
            out, overflow = final_fn(merged, fcap, max_iters)
            self._pending_overflow.append(overflow)
            yield out

    def _exec_global_agg(self, node, in_types, layouts) -> Page:
        partial_fn = self._jit(
            ("gagg_partial", node.aggregates,
             tuple(tuple(l) for l in layouts)),
            functools.partial(
                _partial_global_agg, node.aggregates,
                tuple(tuple(l) for l in layouts)
            ),
        )
        tail = self._fused_partial_tail(node, layouts, None, None)
        fused = (
            self._fused_stream(
                node.source, agg_tail=tail,
                key_extra=("global", node.aggregates,
                           tuple(tuple(l) for l in layouts)))
            if tail is not None else None
        )
        if fused is not None:
            partials = list(fused)
        else:
            partials = [partial_fn(p) for p in self.pages(node.source)]
        if not partials:
            partials = [
                _empty_state_page(node.aggregates, layouts,
                                      collect_k=self._collect_k_eff)
            ]
        merged = concat_all(partials) if len(partials) > 1 else partials[0]
        final_fn = self._jit(
            ("gagg_final", node.aggregates,
             tuple(tuple(l) for l in layouts), tuple(in_types)),
            functools.partial(
                _final_global_agg, node.aggregates,
                tuple(tuple(l) for l in layouts), tuple(in_types)
            ),
        )
        return final_fn(merged)

    # ------------------------------------------------- spill / partitions
    def estimate_rows(self, node: P.PhysicalNode) -> int:
        """Static (host-only) row-count upper estimate for spill planning
        (reference analog: the stats AddExchanges consults; ours derives
        from connector row counts — no selectivity model, conservative)."""
        if isinstance(node, P.TableScan):
            return self.catalogs[node.catalog].row_count(node.table)
        if isinstance(node, P.Values):
            return len(node.rows)
        if isinstance(node, P.Limit):
            return min(node.count + node.offset,
                       self.estimate_rows(node.source))
        if isinstance(node, P.TopN):
            return min(node.limit, self.estimate_rows(node.source))
        if isinstance(node, P.Aggregation):
            if not node.group_channels:
                return 1
            return min(node.capacity, self.estimate_rows(node.source))
        if isinstance(node, P.HashJoin):
            left = self.estimate_rows(node.left)
            if node.join_type in ("semi", "anti"):
                return left
            return max(left, self.estimate_rows(node.right))
        if isinstance(node, P.CrossJoin):
            return self.estimate_rows(node.left) * max(
                self.estimate_rows(node.right), 1
            )
        if isinstance(node, P.Union):
            return sum(self.estimate_rows(s) for s in node.sources)
        if isinstance(node, P.GroupId):
            return self.estimate_rows(node.source) * len(node.set_masks)
        if isinstance(node, P.Unnest):
            # expansion factor unknown statically; modest heuristic
            return self.estimate_rows(node.source) * 4
        if isinstance(node, P.RemoteSource):
            # adaptive execution (ISSUE 15): an OBSERVED exchange row
            # count stamped by the stage-boundary re-planner beats any
            # static estimate — downstream grace partitioning and
            # governor shares then size from measured cardinality
            if node.est_rows is not None:
                return max(int(node.est_rows), 1)
            # fragment edge: estimate from the producer's root when it
            # rides along (origin) — a conservative over-estimate (the
            # FULL producer output; a repartition consumer sees ~1/N),
            # which sizes non-leaf join builds sensibly instead of
            # starting every stage-DAG buffer at the 1-row floor
            if node.origin is not None:
                return self.estimate_rows(node.origin)
            return 1
        kids = node.children()
        return self.estimate_rows(kids[0]) if kids else 1

    def _partition_filter(self, keys: Tuple[int, ...], parts: int,
                          keep_nulls: bool = False):
        """Jitted page transform keeping only rows whose key hash lands in
        partition p (p is traced: one compile serves every pass).

        Partitioning uses the HIGH hash bits: the group-by/join hash
        tables bucket on the low bits (h & (cap-1), ops/agg.py), and
        parts is a power of two — low-bit partitioning would fix those
        bits and cluster every pass's keys into cap/parts slots,
        inflating probe chains ~parts-fold.

        keep_nulls=True routes null-key rows into EVERY pass: semi/anti
        joins need the global "build side contains NULL" fact per pass
        for NOT IN three-valued logic (a null build row otherwise lands
        in exactly one partition and the other passes wrongly emit
        unmatched probe rows as definite non-matches)."""

        def fn(page: Page, p):
            blocks = [page.block(c) for c in keys]
            cols, nulls = K.block_key_columns(blocks)
            h = H.hash_columns(cols, nulls)
            keep = ((h >> jnp.uint64(32)) % jnp.uint64(parts)) == p
            if keep_nulls:
                any_null = jnp.zeros(page.valid.shape, dtype=jnp.bool_)
                for b in blocks:
                    if b.nulls is not None:
                        any_null = any_null | b.nulls
                keep = keep | any_null
            return Page(blocks=page.blocks, valid=page.valid & keep)

        return self._jit(("partfilter", keys, parts, keep_nulls), fn)

    def _spill_partitions(self, est_bytes: int) -> int:
        if self.spill_bytes is None or est_bytes <= self.spill_bytes:
            return 1
        return min(_next_pow2(-(-est_bytes // self.spill_bytes)), 256)

    def _keys_partitionable(self, types, keys) -> bool:
        """Partition hashing is value-consistent only for non-dictionary
        columns (dictionary codes are page-local); string keys disable
        partitioned mode for the operator."""
        return not any(T.is_string(types[c]) for c in keys)

    def _cheap_to_recompute(self, node: P.PhysicalNode) -> bool:
        """Whether re-executing this subtree per pass is acceptable:
        pure scan pipelines recompute pages from row indices (generator
        connectors, SURVEY §8.2.6) or restage from the connector's own
        host store — no join/agg/sort work is repeated."""
        if isinstance(node, (P.TableScan, P.Values)):
            return True
        if isinstance(
            node, (P.Filter, P.Project, P.Exchange, P.Limit, P.Output)
        ):
            return self._cheap_to_recompute(node.source)
        if isinstance(node, P.Union):
            return all(self._cheap_to_recompute(s) for s in node.sources)
        return False

    def _source_stream(self, node: P.PhysicalNode):
        """A callable yielding a fresh page stream for node, for
        operators that consume a source MULTIPLE times (partitioned
        passes). Expensive subtrees materialize once into a PageStore
        (device page list, or host RAM above host_spill_bytes) and
        restream from it — the fix for partitioned passes compounding
        recomputation down a join/agg pipeline (reference: PagesIndex /
        FileSingleStreamSpiller; SURVEY §6.4)."""
        if self._cheap_to_recompute(node):
            return lambda: self.pages(node)
        from presto_tpu.exec.pagestore import PageStore

        # keyed by the (frozen, hashable) plan node itself: identical
        # subtrees share one materialization, and a key can never alias
        # a different plan the way a recycled id() could
        key = node
        if key not in self._stream_cache:
            # NOTE: estimate_rows is a heuristic, not an upper bound —
            # a many-to-many join can exceed max(left, right); a wrong
            # device-tier pick costs HBM headroom, never correctness
            est = self.estimate_rows(node) * _row_bytes(
                self.output_types(node)
            )
            budget = self._budget()
            store_share = (
                budget // MB.STORE_SHARE_DIV if budget else None
            )
            if (self.disk_spill_bytes is not None
                    and est > self.disk_spill_bytes):
                tier = "disk"
            elif (self.host_spill_bytes is not None
                    and est > self.host_spill_bytes):
                tier = "host"
            elif store_share is not None and est > store_share:
                # governed overflow home (membudget.py): an
                # intermediate that cannot stay HBM-resident under the
                # budget stages to host RAM — and past several budgets'
                # worth, to the pagestore disk tier — even when no
                # explicit spill threshold was configured
                tier = (
                    "disk"
                    if est > max(budget * 4, MB.CPU_BUDGET) else "host"
                )
                self.memory_chunked_pipelines += 1
            else:
                tier = "device"
            store = PageStore(tier, spill_dir=self.spill_path)
            for page in self.pages(node):
                store.put(page)
            if tier == "host":
                self.host_spill_pages += store.page_count
                self.host_spill_bytes_used += store.bytes
            elif tier == "disk":
                self.disk_spill_pages += store.page_count
            self._stream_cache[key] = store
        return self._stream_cache[key].stream

    # --------------------------------------------------------------- join
    def _generated_join_info(self, node: P.HashJoin, left_types):
        """Eligibility for the build-free GENERATED join: the build
        subtree is a Filter/Project/Exchange chain over a TableScan of a
        connector that can (a) invert the join-key column in closed form
        (Connector.key_inverse) and (b) generate its columns at
        arbitrary row indices (Connector.gen_at). Then probe keys map to
        build TABLE rows arithmetically and the carried columns are
        GENERATED at those rows — the join holds zero device state: no
        hash table, no searchsorted, no HBM gathers, no capacity
        overflow, no partitioning at any scale factor.

        This is the TPU-native collapse of the reference's
        HashBuilderOperator + LookupJoinOperator for deterministic
        generator tables ("scan == generate", SURVEY §8.2.6, taken to
        its logical end: "lookup == generate")."""
        if not self.generated_join:
            return None
        if node.join_type not in ("inner", "left"):
            return None
        walked = self._scan_chain(node.right, through_joins=False)
        if walked is None:
            return None
        cur, chain = walked

        def plain_int(t) -> bool:
            return not (
                T.is_string(t) or t.is_dictionary_encoded
                or T.is_floating(t)
                or (isinstance(t, T.DecimalType) and not t.is_short)
            )

        if not all(plain_int(left_types[c]) for c in node.left_keys):
            return None
        from presto_tpu.expr.ir import InputRef

        def resolve(ch: int) -> Optional[int]:
            # build-root channel -> scan channel through the projects
            for nd in chain:
                if isinstance(nd, P.Project):
                    e = nd.exprs[ch]
                    if not isinstance(e, InputRef):
                        return None
                    ch = e.channel
            return ch

        conn = self.catalogs[cur.catalog]
        n_rows = conn.row_count(cur.table)
        gen = conn.gen_at(cur.table, cur.columns)
        if gen is None or n_rows <= 0:
            return None
        # ONE key must invert in closed form; the remaining key pairs
        # become equality checks against the generated build columns
        inv, pivot, window, gen_keys = None, None, 1, None
        for j, rk in enumerate(node.right_keys):
            sc = resolve(rk)
            if sc is None:
                continue
            inv = conn.key_inverse(cur.table, cur.columns[sc])
            if inv is not None:
                pivot = j
                break
        if inv is None and self._capacity_boost == 1:
            # windowed inverse (slot-structured fact tables): the pivot
            # key pins an L-slot candidate window; the OTHER keys must
            # resolve to scan columns so the kernel can generate them
            # per candidate and pick the unique full-key match. A probe
            # row matching >1 candidates (key set not unique in data)
            # raises the deferred flag and the boosted retry takes the
            # general join — windowed is ineligible at boost > 1.
            for j, rk in enumerate(node.right_keys):
                sc = resolve(rk)
                if sc is None:
                    continue
                wi = conn.key_window_inverse(cur.table, cur.columns[sc])
                if wi is None:
                    continue
                extra_sc = [
                    resolve(rkk)
                    for jj, rkk in enumerate(node.right_keys) if jj != j
                ]
                if not extra_sc or any(s is None for s in extra_sc):
                    # no extra keys to pin the line (near-certain
                    # multi-match), or unresolvable ones
                    continue
                inv, window = wi
                pivot = j
                gen_keys = conn.gen_at(
                    cur.table, tuple(cur.columns[s] for s in extra_sc)
                )
                break
        if inv is None or (window > 1 and gen_keys is None):
            return None
        extra_pairs = tuple(
            (lk, rk)
            for j, (lk, rk) in enumerate(
                zip(node.left_keys, node.right_keys))
            if j != pivot
        )
        schema = conn.table_schema(cur.table)
        scan_types = tuple(schema.column_type(c) for c in cur.columns)
        dicts = getattr(conn, "_dicts", {}).get(cur.table, {})
        scan_dicts = tuple(dicts.get(c) for c in cur.columns)
        # replay the chain top-down over generated pages (bottom-up in
        # plan order = reversed walk order)
        chain_fns = [
            fn for fn in (
                _node_replay_fn(nd) for nd in reversed(chain)
            ) if fn is not None
        ]
        return (node.left_keys[pivot], extra_pairs, inv, window,
                gen_keys, gen, scan_types, scan_dicts,
                tuple(chain_fns), n_rows)

    @staticmethod
    def generated_join_kernel(node: P.HashJoin, info):
        """The ONE place the _generated_join_info tuple meets the
        kernels: returns (page_fn, windowed). Plain mode: page -> page.
        Windowed: page -> (page, multi_flag) — the caller must defer
        multi_flag to the overflow ladder. Shared by the local executor,
        the dist executor's shard_map wrapping, and the fused-pipeline
        builder."""
        (pivot_ch, extra_pairs, inv, window, gen_keys, gen,
         scan_types, scan_dicts, chain_fns, n_rows) = info
        if window == 1:
            return functools.partial(
                _generated_join_page, pivot_ch, extra_pairs,
                node.join_type, inv, gen, scan_types, scan_dicts,
                chain_fns, n_rows,
            ), False
        return functools.partial(
            _generated_join_window_page, pivot_ch, extra_pairs,
            node.join_type, inv, window, gen_keys, gen, scan_types,
            scan_dicts, chain_fns, n_rows,
        ), True

    def _exec_join_generated(self, node: P.HashJoin, info
                             ) -> Iterator[Page]:
        self.generated_joins_used += 1
        kern, windowed = self.generated_join_kernel(node, info)
        if not windowed:
            fn = self._jit(("genjoin", node), kern)
            for page in self.pages(node.left):
                yield fn(page)
            return
        fn = self._jit(("genjoin_win", node), kern)
        for page in self.pages(node.left):
            out, multi = fn(page)
            # >1 in-window matches for some probe row: the key set is
            # not unique in the data — retry takes the general join
            self._pending_overflow.append(multi)
            yield out

    def _exec_join(self, node: P.HashJoin) -> Iterator[Page]:
        """Page-level join execution: lazy (late-materialization) items
        produced along the probe spine materialize HERE, at the chain
        boundary — every deferred build column pays its one gather."""
        for item in self._exec_join_items(node):
            yield self._materialize_lazy(item)

    def _exec_join_items(self, node: P.HashJoin, want_lazy: bool = False):
        """Yields Page or latemat.LazyPage items for a join node. The
        single-pass general/unique sort paths defer build sides
        (inner/left joins) and consume the probe side through
        _lazy_pages so chained joins compose row-id indirections; every
        other path (generated, Pallas-unique, partitioned, semi/anti,
        right/full) yields materialized Pages as before.

        want_lazy: the consumer is a lazy-aware parent join — defer
        unconditionally. Otherwise (the chain boundary, where the
        caller materializes immediately) defer only when the probe
        items are themselves lazy: deferring the boundary join's own
        side is then free (the finish program runs anyway), while for
        a single un-chained join it would only add a launch."""
        left_types = self.output_types(node.left)
        right_types = self.output_types(node.right)
        gj = self._generated_join_info(node, left_types)
        if gj is not None:
            yield from self._exec_join_generated(node, gj)
            return
        # <=1 match per probe row when ANY build key scans a connector-
        # declared unique column (equality on a unique column alone
        # pins the row): join output can never exceed the probe page,
        # so output capacities stay exact (FK joins — the TPC-H common
        # case)
        unique_build = any(
            self._scan_column_unique(node.right, k)
            for k in node.right_keys
        )
        parts, governed = self._join_parts(node, left_types, right_types)
        if parts > 1:
            if governed:
                # the GOVERNOR (not a session threshold) rewrote this
                # join into grace-partition passes sized to fit
                self.memory_chunked_pipelines += 1
            yield from self._exec_join_partitioned(
                node, parts, left_types, right_types, unique_build
            )
            return
        build_pages = list(self.pages(node.right))
        if not build_pages:
            build_pages = [_empty_page(right_types)]
        build_all = concat_all(build_pages)
        # capacity-based sizing, not row count: reading num_rows() to the
        # host mid-query would trigger the axon post-D2H degradation (see
        # __init__); capacity is a static upper bound on rows
        build = compact_page(build_all, _next_pow2(build_all.capacity))
        self._account_page(build)  # the query's largest materialization
        if self._pallas_join_eligible(node, build, left_types,
                                      right_types):
            yield from self._pallas_join_pass(node, build, left_types)
            return
        allow = (self._late_mat_on()
                 and node.join_type in ("inner", "left"))
        probe_src = (
            self._lazy_pages(node.left) if allow
            else self.pages(node.left)
        )
        defer = "never"
        if allow:
            defer = "always" if want_lazy else "chain"
        yield from self._join_pass(
            node, build, probe_src, left_types,
            unique_build=unique_build, defer=defer,
        )

    # --------------------------------------- late materialization driver
    def _late_mat_on(self) -> bool:
        """late_materialization_enabled resolution: "auto" engages on
        TPU only (gather bandwidth is the win; CPU pays compile cost
        for nothing), True/False are explicit overrides."""
        mode = self.late_mat
        if mode in (False, None, "false", "off"):
            return False
        if mode == "auto":
            return jax.default_backend() == "tpu"
        return True

    def _lazy_probe_ok(self, node: P.PhysicalNode) -> bool:
        """Whether a probe-side subtree may stream lazy items instead of
        Pages. The DistExecutor narrows this to fully-replicated
        subtrees (sharded nodes route through shard_map paths that
        speak Pages)."""
        return self._late_mat_on()

    def _lazy_pages(self, node: P.PhysicalNode):
        """A join's probe-side stream: latemat.LazyPage items when the
        subtree is an eligible join-chain segment, plain Pages
        otherwise. Whole-chain fusion (generated joins) wins over
        laziness — a fused chain has no gathers to defer.

        Items bypass pages(), so interior chain nodes get no per-node
        EXPLAIN ANALYZE stats (the chain's wall lands on the top join);
        memory accounting is preserved by accounting every interior
        item here."""
        if isinstance(node, P.HashJoin) and self._lazy_probe_ok(node):
            fused = self._fused_stream(node)
            if fused is not None:
                for page in fused:
                    self._account_page(page)
                    yield page
                return
            for item in self._exec_join_items(node, want_lazy=True):
                self._account_page(
                    item.reduced if isinstance(item, LM.LazyPage)
                    else item
                )
                yield item
            return
        if (isinstance(node, P.Filter) and self._lazy_probe_ok(node)
                and _filter_chain_has_join(node)):
            fused = self._fused_stream(node)
            if fused is not None:
                for page in fused:
                    self._account_page(page)
                    yield page
                return
            yield from self._lazy_filter(node)
            return
        yield from self.pages(node)

    def _lazy_filter(self, node: P.Filter):
        """Filter over a lazy join chain: lift exactly the deferred
        channels the predicate reads (prune.expr_channels — the
        liveness set), remap the predicate onto the reduced layout, and
        flip validity bits without materializing anything else."""
        refs = tuple(sorted(PR.expr_channels(node.predicate)))
        for item in self._lazy_pages(node.source):
            if isinstance(item, Page):
                fn = self._jit(
                    ("filter", node.predicate),
                    functools.partial(_replay_filter, node.predicate),
                )
                yield fn(item)
                continue
            lz = self._lazy_lift(item, refs)
            pred = PR.remap_expr(
                node.predicate, {c: lz.phys(c) for c in refs}
            )
            fn = self._jit(
                ("filter_lazy", pred, lz.mat),
                functools.partial(_replay_filter, pred),
            )
            yield dataclasses.replace(lz, reduced=fn(lz.reduced))

    def _lazy_lift(self, lz: LM.LazyPage, channels) -> LM.LazyPage:
        """Materialize the named logical channels of a lazy page (one
        gather each) — downstream join keys and filter references, the
        'needed as values NOW' set. No-op when already materialized."""
        need = tuple(sorted(set(channels) - set(lz.mat)))
        if not need:
            return lz
        self.gathers_materialized += len(need)
        maps = tuple(s.channel_map for s in lz.sides)
        fn = self._jit(
            ("latemat_lift", lz.signature(), need,
             tuple(s.build.capacity for s in lz.sides)),
            functools.partial(LM.lift_page, lz.mat, maps, need),
        )
        reduced = fn(lz.reduced, *[s.build for s in lz.sides])
        _, new_mat, new_maps, keep = LM.lift_layout(lz.mat, maps, need)
        return LM.LazyPage(
            reduced=reduced, width=lz.width, mat=new_mat,
            sides=tuple(
                LM.LazySide(lz.sides[i].build, new_maps[i])
                for i in keep
            ),
        )

    def _materialize_lazy(self, item):
        """Chain-boundary materialization: every still-deferred column
        gathers exactly once through its side's composed row ids."""
        if isinstance(item, Page):
            return item
        if not item.sides:
            return item.reduced  # mat covers all channels, in order
        self.gathers_materialized += sum(
            len(s.channel_map) for s in item.sides
        )
        maps = tuple(s.channel_map for s in item.sides)
        fn = self._jit(
            ("latemat_fin", item.signature(),
             tuple(s.build.capacity for s in item.sides)),
            functools.partial(
                LM.finish_page, item.mat, maps, item.width
            ),
        )
        return fn(item.reduced, *[s.build for s in item.sides])

    # ---------------------------------------------------- Pallas paths
    def _pallas_mode_allows(self, layout) -> bool:
        """pallas_join_enabled semantics: "off" never; "force" always
        (oversized/unlowerable layouts run the kernels in interpret
        mode — the CPU test path); "auto" only layouts whose kernel
        REALLY lowers through Mosaic, and only on TPU (the interpreted
        kernels exist for testing, not speed)."""
        from presto_tpu.ops import pallas_join as PJ

        mode = self.pallas_join
        if mode in (False, None, "off"):
            return False
        if mode in (True, "force"):
            return True
        return (
            jax.default_backend() == "tpu"
            and PJ.layout_lowers_on_tpu(layout)
        )

    @staticmethod
    def _pallas_interpret(layout) -> bool:
        from presto_tpu.ops import pallas_join as PJ

        return not (
            jax.default_backend() == "tpu"
            and PJ.layout_lowers_on_tpu(layout)
        )

    def _pallas_join_eligible(self, node, build: Page, left_types,
                              right_types) -> bool:
        """Unique-key fast path: inner/left joins on ONE u64-encodable
        key whose build side scans a connector-declared UNIQUE column —
        <=1 match per probe row, so the probe page extends in place with
        no match expansion at all. Boosted retries fall back to the
        general join (the overflow flag may have come from the Pallas
        table build)."""
        from presto_tpu.ops import pallas_join as PJ

        if self._capacity_boost > 1:
            return False
        if node.join_type not in ("inner", "left"):
            return False
        if len(node.left_keys) != 1 or len(node.right_keys) != 1:
            return False
        for t in (left_types[node.left_keys[0]],
                  right_types[node.right_keys[0]]):
            if T.is_string(t) or t.is_dictionary_encoded:
                # dictionary codes are not comparable across sides
                # without the merged-universe canonicalization the
                # general path does
                return False
            if isinstance(t, T.DecimalType) and not t.is_short:
                # long decimals encode as (hi, lo) limb pairs — one u64
                # key cannot carry them
                return False
        if build.capacity > PJ.RADIX_MAX_BUILD:
            return False
        if not self._pallas_mode_allows(PJ.plan_layout(build.capacity)):
            return False
        return self._scan_column_unique(node.right, node.right_keys[0])

    def _radix_join_eligible(self, node, build: Page) -> bool:
        """The radix-partitioned Pallas join (ops/pallas_join.py) as the
        general range finder for inner/left/right/full equi-joins: any
        key count/types, duplicate build keys. On TPU (auto) it engages
        for layouts whose kernel really lowers (the dim layout — star-
        schema dimension builds); forced mode additionally runs the
        bucketed radix kernel in interpret mode up to RADIX_MAX_BUILD
        rows (the CPU test path). Boosted retries fall back to the sort
        join — the overflow may have been a bucket overfull in the
        Pallas table build."""
        if self._capacity_boost > 1:
            return False
        if node.join_type not in ("inner", "left", "right", "full"):
            return False
        from presto_tpu.ops import pallas_join as PJ

        if build.capacity > PJ.RADIX_MAX_BUILD:
            return False
        return self._pallas_mode_allows(PJ.plan_layout(build.capacity))

    def _scan_column_unique(self, n: P.PhysicalNode, ch: int) -> bool:
        """Whether channel ch of node n provably carries a unique table
        column (shared walker: P.scan_column_unique, also used by the
        planner's join ordering)."""
        return P.scan_column_unique(n, ch, self.catalogs)

    def _pallas_join_pass(self, node, build: Page,
                          left_types) -> Iterator[Page]:
        from presto_tpu.ops import pallas_join as PJ

        self.pallas_joins_used += 1
        self.pallas_kernels_used += 1
        layout = PJ.plan_layout(build.capacity)
        interpret = self._pallas_interpret(layout)
        index, build_ovf = self._jit(
            ("pallas_ubuild", node.right_keys[0], build.capacity),
            functools.partial(
                _pallas_unique_build, node.right_keys[0], layout
            ),
        )(build)
        self._pending_overflow.append(build_ovf)
        fn = self._jit(
            ("pallas_probe", node.left_keys[0], node.join_type,
             build.capacity, interpret),
            functools.partial(
                _pallas_probe_page, node.left_keys[0], node.join_type,
                layout, interpret,
            ),
        )
        for page in self.pages(node.left):
            yield fn(page, build, index)

    def _exec_join_partitioned(
        self, node: P.HashJoin, parts: int, left_types, right_types,
        unique_build: bool = False,
    ) -> Iterator[Page]:
        """Grace-style partition-wise join: P passes, each streaming both
        sides filtered to hash(key) % P == p, so the build materialization
        is ~1/P of the single-pass size. Skewed partitions raise the
        deferred overflow flag and the query retries on the boosted
        capacity ladder — where INNER joins take the per-partition
        REBALANCING path instead of growing buffers (SURVEY §6.7):
        a genuinely hot join key cannot be split by key hash, so the hot
        partition's build rows are chunked by POSITION into passes whose
        buffers stay at the unboosted (fault-line-safe) size, each chunk
        probed by the full partition probe stream; inner-join output is
        the disjoint union over chunks (every build row lives in exactly
        one chunk). Reading exact partition sizes is a host sync, which
        is admissible here because the retry boundary already paid the
        one D2H read that triggers axon's post-read degradation."""
        self.spill_partitions_used = max(self.spill_partitions_used, parts)
        semi = node.join_type in ("semi", "anti")
        bfilter = self._partition_filter(node.right_keys, parts,
                                         keep_nulls=semi)
        pfilter = self._partition_filter(node.left_keys, parts)
        right_stream = self._source_stream(node.right)
        left_stream = self._source_stream(node.left)
        rebalance = (
            self.join_skew_rebalance
            and (self._capacity_boost > 1 or self.skew_preengaged)
            and node.join_type == "inner"
        )
        if rebalance and self._capacity_boost == 1:
            # adaptive pre-engagement (ISSUE 15): the stage-boundary
            # re-planner saw a hot partition in the upstream spool
            # histogram, so the rebalanced chunking starts on the
            # FIRST attempt instead of being discovered via overflow
            self.skew_preempted += 1
        for p in range(parts):
            pj = jnp.uint64(p)
            if rebalance:
                yield from self._join_partition_rebalanced(
                    node, p, parts, bfilter, pfilter, right_stream,
                    left_stream, left_types, unique_build,
                )
                continue
            build_pages = []
            for pg in right_stream():
                f = bfilter(pg, pj)
                # compact each filtered build page to ~pg/parts before the
                # concat — this is where the memory actually shrinks
                pc = min(
                    _next_pow2(
                        max(pg.capacity // parts * 2, 1024)
                        * self._capacity_boost
                    ),
                    _next_pow2(pg.capacity),
                )
                self._pending_overflow.append(f.num_rows() > pc)
                build_pages.append(compact_page(f, pc))
            if not build_pages:
                build_pages = [_empty_page(right_types)]
            build_all = concat_all(build_pages)
            build = compact_page(build_all, _next_pow2(build_all.capacity))
            self._account_page(build)
            probe_pages = (
                pfilter(pg, pj) for pg in left_stream()
            )
            # partition-filtered probe pages are ~1/parts dense — scale
            # output capacities down accordingly or every pass's output
            # pages balloon to unpartitioned size (and a downstream
            # materialization would pin parts-times the real data)
            yield from self._join_pass(node, build, probe_pages,
                                       left_types,
                                       unique_build=unique_build,
                                       density=parts)

    def _join_partition_rebalanced(
        self, node: P.HashJoin, p: int, parts: int, bfilter, pfilter,
        right_stream, left_stream, left_types, unique_build: bool,
    ) -> Iterator[Page]:
        """One skew-rebalanced partition pass (see _exec_join_partitioned):
        exact per-page build counts (host reads — recovery mode), pieces
        packed greedily into chunks of at most the UNBOOSTED partition
        cap, oversized pieces split by slice_page, one probe pass per
        chunk."""
        from presto_tpu.ops.compact import slice_page

        pj = jnp.uint64(p)
        chunk_cap = 1024
        pieces: List[Page] = []
        for pg in right_stream():
            chunk_cap = max(
                chunk_cap,
                min(SH.chunk_bucket(pg.capacity, parts),
                    _next_pow2(pg.capacity)),
            )
            f = bfilter(pg, pj)
            # host sync, admissible on retry — metered (exec/xfer.py)
            n = int(XF.np_host(f.num_rows(), label="skew-count"))
            if n:
                pieces.append(compact_page(f, _next_pow2(max(n, 256))))
        # greedy pack: pieces accumulate into a chunk until it would
        # exceed chunk_cap; a single piece larger than chunk_cap splits
        # by position
        chunks: List[List[Page]] = [[]]
        room = chunk_cap
        for piece in pieces:
            rows = piece.capacity  # compacted: capacity ~ rows
            if rows > chunk_cap:
                for off in range(0, rows, chunk_cap):
                    chunks.append(
                        [slice_page(piece, off, chunk_cap)]
                    )
                # the split chunks are full (and `room` still described
                # the chunk BEFORE them): start a fresh chunk so later
                # pieces cannot pile onto a full slice and grow a chunk
                # to ~2x chunk_cap
                chunks.append([])
                room = chunk_cap
                continue
            if rows > room:
                chunks.append([])
                room = chunk_cap
            chunks[-1].append(piece)
            room -= rows
        chunks = [c for c in chunks if c]
        if not chunks:
            return  # empty inner partition: no output
        self.skew_chunks_used = max(self.skew_chunks_used, len(chunks))
        for chunk in chunks:
            build_all = concat_all(chunk)
            build = compact_page(
                build_all, _next_pow2(build_all.capacity)
            )
            self._account_page(build)
            probe_pages = (pfilter(pg, pj) for pg in left_stream())
            yield from self._join_pass(
                node, build, probe_pages, left_types,
                unique_build=unique_build, density=parts,
            )

    def _join_pass(
        self, node: P.HashJoin, build: Page, probe_pages, left_types,
        *, unique_build: bool = False, density: int = 1,
        defer: str = "never",
    ):
        """One build+probe pass (the whole join unless partitioned).

        unique_build: <=1 match per probe row — output sized to the probe
        page exactly. density: probe pages carry ~1/density real rows
        (partition-filtered passes); output capacity shrinks to match,
        with the deferred overflow flag + boosted retry guarding skew.
        defer: "always" emits this join's build side as a row-id
        indirection (latemat.LazyPage) instead of gathering its columns;
        "chain" defers only when the probe item is itself lazy (the
        finish program runs anyway, so deferring is free — while for a
        lone boundary join it would just add a launch); "never" is the
        eager path. Lazy probe items' deferred keys lift here — exactly
        the 'needed as a downstream join key' liveness contract."""
        if node.join_type in ("semi", "anti"):
            fn = self._jit(
                ("semi", node.left_keys, node.right_keys,
                 build.capacity),
                functools.partial(_semi_join_page, node.left_keys,
                                  node.right_keys),
            )
            for page in probe_pages:
                yield fn(page, build)
            return

        # Radix Pallas path: same verified match expansion, but the
        # candidate ranges come from the bucketed open-addressing kernel
        # instead of searchsorted (north-star's radix-partitioned join)
        use_radix = self._radix_join_eligible(node, build)
        layout = interpret = None
        if use_radix:
            from presto_tpu.ops import pallas_join as PJ

            self.pallas_joins_used += 1
            self.pallas_kernels_used += 1
            layout = PJ.plan_layout(build.capacity)
            interpret = self._pallas_interpret(layout)
        use_unique = (
            not use_radix and unique_build
            and node.join_type in ("inner", "left")
            and self._capacity_boost == 1
        )
        defer_allowed = (
            defer != "never" and node.join_type in ("inner", "left")
        )

        def probe_fn_for(pkeys, defer_item):
            if use_radix:
                return self._jit(
                    ("radix_probe", node.right_keys, node.join_type,
                     build.capacity, interpret, pkeys, defer_item),
                    functools.partial(
                        _probe_radix_join_page, pkeys,
                        node.right_keys, node.join_type, layout,
                        interpret, defer_item,
                    ),
                    static_argnums=(3,),
                )
            if use_unique:
                # FK fast path: no expansion; a u64 hash collision
                # between distinct unique keys flags overflow and the
                # boosted retry takes the general expansion below
                return self._jit(
                    ("join_probe_unique", node.right_keys,
                     node.join_type, build.capacity, pkeys,
                     defer_item),
                    functools.partial(
                        _probe_join_page_unique, pkeys,
                        node.right_keys, node.join_type, defer_item,
                    ),
                    static_argnums=(3,),
                )
            return self._jit(
                ("join_probe", node.right_keys, node.join_type,
                 build.capacity, pkeys, defer_item),
                functools.partial(
                    _probe_join_page, pkeys, node.right_keys,
                    node.join_type, defer_item,
                ),
                static_argnums=(3,),
            )

        build_matched = jnp.zeros((build.capacity,), dtype=jnp.bool_)
        n_right = len(build.blocks)
        # governed output-capacity ceiling (membudget.py): a join
        # output page claims at most its budget share and stays under
        # the device fault line; a page whose naturally-sized output
        # would exceed it is position-chunked below
        out_row_b = _row_bytes(left_types) + _row_bytes(
            [b.type for b in build.blocks]
        )
        oc_cap = MB.rows_cap(
            out_row_b, self._budget(), self._fault_rows(),
            MB.PAGE_SHARE_DIV,
        )
        chunk_counted = False
        # canonical key encodings depend on the probe page's dictionaries
        # (merged-universe remap), which can differ across pages when the
        # probe side unions differently-coded streams — index per
        # dictionary signature, built once each (HashBuilderOperator
        # analog; one signature in the common case)
        indexes: Dict = {}
        for item in probe_pages:
            if isinstance(item, LM.LazyPage):
                # downstream-join-key liveness: lift exactly the key
                # channels this probe needs as values
                lz = self._lazy_lift(item, node.left_keys)
                page = lz.reduced
                pkeys = tuple(lz.phys(c) for c in node.left_keys)
            else:
                lz = None
                page = item
                pkeys = tuple(node.left_keys)
            sig = (pkeys,
                   tuple(page.block(c).dictionary for c in pkeys))
            if sig not in indexes:
                if use_radix:
                    index, b_ovf = self._jit(
                        ("radix_build", node.right_keys,
                         build.capacity, sig),
                        functools.partial(
                            _build_radix_join_index, pkeys,
                            node.right_keys, layout,
                        ),
                    )(page, build)
                    # bucket-overfull escape: boosted retries fall back
                    # to the sort join (eligibility checks the boost)
                    self._pending_overflow.append(b_ovf)
                else:
                    index = self._jit(
                        ("join_build", node.right_keys,
                         build.capacity, sig),
                        functools.partial(
                            _build_join_index, pkeys,
                            node.right_keys,
                        ),
                    )(page, build)
                indexes[sig] = index
            index = indexes[sig]
            # probe-relative sizing (many-to-one joins dominate), with a
            # build term for small-probe fan-out joins, clamped so the 2x
            # term cannot COMPOUND down a join chain (each join's output
            # page is the next probe's input; Q17's 7-join pipeline would
            # double 262k -> 4.2M and cross the >=4M-row axon kernel
            # fault line). Real fan-out beyond the clamp lands on the
            # overflow-retry ladder (up to 4^5 x).
            if unique_build:
                # output rows <= probe rows, exactly sized
                oc = page.capacity
            else:
                oc = page.capacity * 2
                if page.capacity <= 1 << 16:
                    oc = max(oc, build.capacity)
            oc = min(oc, max(4 * self.page_rows, 1 << 19))
            if density > 1:
                # 2x slack over the expected 1/density occupancy absorbs
                # partition-hash fluctuation without a boosted retry
                oc = max(oc * 2 // density, 8192)
            oc = _next_pow2(max(oc, 8192) * self._capacity_boost)
            slices = 1
            if oc_cap is not None and oc > oc_cap:
                # probe-side POSITION chunking (the governed rewrite):
                # slice the probe page so each slice keeps the full
                # per-probe-row output allowance inside a cap-sized
                # buffer. Boosted retries grow `oc`, hence the slice
                # count — capacity per probe row still climbs the
                # ladder while the buffer stays at the cap (except the
                # pathological tiny-probe/huge-fan-out corner, where
                # the LADDER_MIN slice floor binds and oc keeps the
                # allowance instead — slots must exist somewhere).
                # Both factors are powers of two, so slice shapes land
                # on the shared ladder and chunk programs are reused.
                slices = min(
                    oc // oc_cap,
                    max(page.capacity // SH.LADDER_MIN, 1),
                )
                oc = max(oc // slices, oc_cap)
                if slices > 1 and not chunk_counted:
                    # counted only when chunking actually happens (the
                    # LADDER_MIN floor can pin slices at 1, in which
                    # case oc simply keeps the allowance)
                    self.memory_chunked_pipelines += 1
                    chunk_counted = True
            defer_item = defer_allowed and (
                defer == "always" or lz is not None
            )
            pfn = probe_fn_for(pkeys, defer_item)
            # ceil-divide: a concat-produced probe page's capacity is a
            # SUM of buckets and need not be a multiple of the slice
            # count — floor division would silently drop the tail rows.
            # slice_page clamps the final slice; recomputing the slice
            # count from the ceil'd chunk keeps every chunk non-empty.
            ccap = -(-page.capacity // max(slices, 1))
            n_slices = -(-page.capacity // max(ccap, 1))
            for s in range(n_slices):
                chunk = (
                    page if n_slices == 1
                    else slice_page(page, s * ccap, ccap)
                )
                out, matched, overflow = pfn(chunk, build, index, oc)
                self._pending_overflow.append(overflow)
                build_matched = build_matched | matched
                if defer_item:
                    width_l = lz.width if lz is not None else (
                        page.channel_count
                    )
                    mat = lz.mat if lz is not None else tuple(
                        range(page.channel_count)
                    )
                    sides = (lz.sides if lz is not None else ()) + (
                        LM.LazySide(
                            build,
                            tuple((width_l + j, j)
                                  for j in range(n_right)),
                        ),
                    )
                    self.gathers_deferred += sum(
                        len(s.channel_map) for s in sides
                    )
                    yield LM.LazyPage(
                        reduced=out, width=width_l + n_right, mat=mat,
                        sides=sides,
                    )
                else:
                    yield out
        if node.join_type in ("right", "full"):
            # emit unmatched build rows with null left side (reference:
            # LookupOuterOperator draining unvisited positions)
            unmatched = build.valid & ~build_matched
            null_left = _null_blocks(left_types, build.capacity)
            page = Page(
                blocks=tuple(null_left) + build.blocks, valid=unmatched
            )
            yield page


# ---------------------------------------------------------------- kernels
# Module-level pure functions so functools.partial(...) stays hashable and
# jit caches hit across pages.


def _pallas_unique_build(key_ch, layout, build: Page):
    """Unique-key Pallas index over the IDENTITY u64 key encoding —
    in-kernel (lo, hi) equality IS key equality, so probe hits extend
    rows without re-verification."""
    from presto_tpu.ops import pallas_join as PJ

    blk = build.block(key_ch)
    bkeys = K.equality_encoding(blk)[0]
    bvalid = build.valid
    if blk.nulls is not None:
        bvalid = bvalid & ~blk.nulls
    tables, perm, ovf = PJ.build_index(
        bkeys.astype(jnp.uint64), bvalid, layout
    )
    return (tables, perm), ovf


def _pallas_probe_page(key_ch, join_type, layout, interpret, page: Page,
                       build: Page, index) -> Page:
    """Probe one page through the Pallas kernel: unique build keys mean
    <=1 match per probe row, so the output page is the probe page
    extended with gathered build columns (no expansion)."""
    from presto_tpu.ops import pallas_join as PJ

    tables, perm = index
    blk = page.block(key_ch)
    pkeys = K.equality_encoding(blk)[0]
    valid_key = page.valid
    if blk.nulls is not None:
        valid_key = valid_key & ~blk.nulls
    start, cnt = PJ.probe_index(
        pkeys.astype(jnp.uint64), tables, layout, interpret=interpret
    )
    hit = valid_key & (cnt > 0)
    rid = jnp.where(
        hit, perm[jnp.clip(start, 0, None)].astype(jnp.int32),
        jnp.int32(-1),
    )
    matched = rid >= 0
    safe = jnp.clip(rid, 0, build.capacity - 1).astype(jnp.int64)
    right_blocks = []
    for b in build.blocks:
        if isinstance(b.data, tuple):
            data = tuple(d[safe] for d in b.data)
        else:
            data = b.data[safe]
        nulls = b.nulls[safe] if b.nulls is not None else None
        if join_type == "left":
            nulls = ~matched if nulls is None else (nulls | ~matched)
        right_blocks.append(
            Block(data=data, type=b.type, nulls=nulls,
                  dictionary=b.dictionary)
        )
    out_valid = (
        page.valid & matched if join_type == "inner" else page.valid
    )
    return Page(blocks=page.blocks + tuple(right_blocks),
                valid=out_valid)


def _project_page(exprs, page: Page) -> Page:
    blocks = []
    for e in exprs:
        v = evaluate(e, page, jnp)
        data = v.data
        if not isinstance(data, tuple) and data.ndim == 0:
            data = jnp.broadcast_to(data, (page.capacity,))
        elif isinstance(data, tuple):
            data = tuple(
                jnp.broadcast_to(d, (page.capacity,)) if d.ndim == 0 else d
                for d in data
            )
        nulls = v.nulls
        if nulls is not None and nulls.ndim == 0:
            nulls = jnp.broadcast_to(nulls, (page.capacity,))
        dic = v.dictionary
        if (dic is None and T.is_string(e.type) and v.is_const
                and v.py_value is not None):
            # a PROJECTED string constant must be first-class: consuming
            # functions resolve constants against the column dictionary,
            # but as an output column the code needs its own one-entry
            # dictionary or it would decode as the bare code 0
            dic = Dictionary([v.py_value])
        blocks.append(
            Block(data=data, type=e.type, nulls=nulls, dictionary=dic)
        )
    return Page(blocks=tuple(blocks), valid=page.valid)


def _group_ids(group_channels, page: Page, cap: int, max_iters: int = 64):
    key_blocks = [page.block(c) for c in group_channels]
    # dense fast path: all keys dictionary-coded (unique values, no nulls) or
    # boolean, and the combined code space fits the capacity — group id is
    # computed arithmetically, no hash table at all (Q1: 2 flag columns).
    # Reference analog: BigintGroupByHash's small-range fast path.
    sizes = []
    for b in key_blocks:
        if (
            b.dictionary is not None
            and len(b.dictionary)
            and not b.dictionary.has_duplicate_values()
            and b.nulls is None
        ):
            sizes.append(len(b.dictionary))
        elif isinstance(b.type, T.BooleanType) and b.nulls is None:
            sizes.append(2)
        else:
            sizes = None
            break
    if sizes is not None:
        space = 1
        for s in sizes:
            space *= s
        if space <= cap:
            gid = jnp.zeros(page.valid.shape, dtype=jnp.int64)
            for b, s in zip(key_blocks, sizes):
                code = jnp.clip(b.data.astype(jnp.int64), 0, s - 1)
                gid = gid * s + code
            # size the output to the key space, not the caller's capacity:
            # downstream segment ops scale with the group capacity (XLA:TPU
            # expands them to dense [n, cap] one-hot products)
            return A.compute_groups_dense(
                gid, page.valid, space, out_capacity=_next_pow2(space),
                sizes=tuple(sizes),
            )
    key_cols, key_nulls = K.block_key_columns(key_blocks)
    if cap > A.MATMUL_AGG_MAX_GROUPS or page.valid.shape[0] >= (1 << 22):
        # High-cardinality group-bys take the packed-argsort path: its
        # sorted layout lets aggregate() run scatter-free (gather +
        # cumsum + boundary diffs — round-4: the hashed while_loop's
        # per-iteration scatters made Q3 SF1's aggregation 42s of a
        # 91s query). Also mandatory >= ~4M rows, where the
        # vectorized-probing while_loop kernel faults the XLA:TPU
        # runtime (observed on v5e regardless of table size).
        return A.compute_groups_sorted(
            key_cols, key_nulls, page.valid, cap
        )
    # small capacities: the probing hash table is cheap and its input-
    # order group ids feed the MXU one-hot matmul aggregation directly
    return A.compute_groups_hashed(
        key_cols, key_nulls, page.valid, cap, max_iters=max_iters
    )


def _state_reduce(st, blk, kind, apply_pre, reducer):
    """Run one primitive reduction with value-domain transforms.

    Dictionary-coded inputs (min/max need *value* order, not code order) are
    rank-transformed through Dictionary.sort_rank before reducing and mapped
    back after, and the dictionary rides along so decode stays correct.
    reducer(data, nulls) -> (vals, out_nulls).
    """
    if blk is None:
        return (*reducer(None, None), None)
    if isinstance(blk.data, tuple):
        raise NotImplementedError(
            "aggregation over long-decimal (p>18) input columns is not "
            "supported yet; decimal sums produce long-decimal *outputs* "
            "from short inputs (presto_tpu/exec/agg_states.py)"
        )
    dic = blk.dictionary
    if dic is not None and kind in (A.MIN, A.MAX) and len(dic):
        # xfercheck: raw-ok - trace-time LUT embedding
        rank = jnp.asarray(dic.sort_rank().astype(np.int64))
        # xfercheck: raw-ok - trace-time LUT embedding
        inv = jnp.asarray(np.argsort(dic.sort_rank()).astype(np.int64))
        data = rank[jnp.clip(blk.data, 0, len(dic) - 1)]
        vals, out_nulls = reducer(data, blk.nulls)
        vals = inv[jnp.clip(vals, 0, len(dic) - 1)].astype(blk.data.dtype)
        return vals, out_nulls, dic
    data = S.pre_transform(st.pre, blk.data) if apply_pre else blk.data
    vals, out_nulls = reducer(data, blk.nulls)
    return vals, out_nulls, dic


def _attach_dictionary(block: Block, dic) -> Block:
    if dic is None or block.dictionary is not None:
        return block
    if not block.type.is_dictionary_encoded:
        return block
    return Block(
        data=block.data, type=block.type, nulls=block.nulls, dictionary=dic
    )


def _mark_distinct_page(mark_channel_sets, page: Page, cap, max_iters):
    """Append first-occurrence marks per key set (MarkDistinctOperator):
    group ids over the key set, then scatter True at each group's
    representative row."""
    blocks: List[Block] = []
    overflow = jnp.zeros((), dtype=jnp.bool_)
    for chans in mark_channel_sets:
        groups = _group_ids(chans, page, cap, max_iters)
        idx = jnp.where(
            groups.group_valid, groups.rep_index, page.capacity
        )
        mark = jnp.zeros((page.capacity,), dtype=jnp.bool_)
        mark = mark.at[idx].set(True, mode="drop")
        blocks.append(Block(data=mark, type=T.BOOLEAN, nulls=None))
        overflow = overflow | groups.overflow
    return (
        Page(blocks=page.blocks + tuple(blocks), valid=page.valid),
        overflow,
    )


def _apply_agg_mask(spec, page: Page, blk: Optional[Block]):
    """Per-aggregate mask (AggSpec.mask): unmarked rows contribute
    nothing — expressed as null inputs, which every accumulator skips."""
    if spec.mask is None or blk is None:
        return blk
    inv = ~page.block(spec.mask).data
    nulls = inv if blk.nulls is None else (blk.nulls | inv)
    return Block(data=blk.data, type=blk.type, nulls=nulls,
                 dictionary=blk.dictionary)


def _hll_hashes(blk: Block) -> jnp.ndarray:
    """One u64 hash per row over the block's equality encoding (SQL-
    equal values hash equal, including dictionary canonicalization)."""
    cols = K.equality_encoding(blk)
    return H.hash_columns(cols, [None] * len(cols))


def _hll_contributing(groups, blk: Optional[Block]):
    contributing = groups.row_valid
    if blk is not None and blk.nulls is not None:
        contributing = contributing & ~blk.nulls
    return contributing


def _dense_keys_page(src: Page, group_channels, groups) -> Page:
    """Synthesize group-key columns arithmetically from the mixed-radix
    group id (dense path): avoids the rep_index scatter+gather, which
    XLA then dead-code-eliminates from the program."""
    out_cap = groups.group_valid.shape[0]
    gid = jnp.arange(out_cap, dtype=jnp.int64)
    codes = []
    for s in reversed(groups.dense_sizes):
        codes.append(gid % s)
        gid = gid // s
    codes.reverse()
    blocks = []
    for c, code in zip(group_channels, codes):
        b = src.block(c)
        blocks.append(
            Block(data=code.astype(b.data.dtype), type=b.type,
                  nulls=None, dictionary=b.dictionary)
        )
    return Page(blocks=tuple(blocks), valid=groups.group_valid)


def _agg_keys_page(src: Page, group_channels, groups) -> Page:
    if groups.dense_sizes is not None:
        return _dense_keys_page(src, group_channels, groups)
    return gather_rows(
        src.select_channels(group_channels),
        groups.rep_index,
        groups.group_valid,
    )


def _collect_encode(blk: Block):
    """Encode a block's values into int64 collect slots (ints/dates/
    bools/short decimals directly, dictionary codes as-is — the
    dictionary rides the state Block).

    Floats use an arithmetic sign/exponent/mantissa pack built from
    log2/exp2/floor only: the axon TPU toolchain compiles NEITHER
    64-bit bitcast_convert_type NOR frexp/ldexp (probed round 4 —
    compiler SIGSEGV / unimplemented X64 rewrite), and its emulated
    float64 is range-limited (~1e38, f32-pair emulation), so the
    exponent fits comfortably in the 11-bit field. The pack is
    ORDER-PRESERVING (int64 order == float order), which is why
    approx_percentile needs no float special case. Values round-trip
    at full device precision; NaN encodes as +max (documented)."""
    data = blk.data
    if isinstance(data, tuple):
        raise NotImplementedError(
            "array_agg/map_agg over long-decimal (p>18) inputs is not "
            "supported"
        )
    if data.dtype in (jnp.float64, jnp.float32):
        x = data.astype(jnp.float64)
        ax = jnp.abs(x)
        safe = jnp.where(ax > 0, ax, 1.0)
        e = jnp.floor(jnp.log2(safe))
        # power-of-two scaling is exact; two correction steps absorb
        # any log2 boundary imprecision
        m = safe * jnp.exp2(-e - 1.0)
        for _ in range(2):
            hi = m >= 1.0
            lo = m < 0.5
            e = e + jnp.where(hi, 1.0, 0.0) - jnp.where(lo, 1.0, 0.0)
            m = jnp.where(hi, m * 0.5, jnp.where(lo, m * 2.0, m))
        frac = jnp.clip(
            ((m - 0.5) * float(2**53)).astype(jnp.int64),
            0, (1 << 52) - 1,
        )
        e_adj = jnp.clip(e.astype(jnp.int64) + 1100, 0, 2047)
        mag = (e_adj << jnp.int64(52)) | frac
        enc = jnp.where(
            ax == 0, jnp.int64(0), jnp.where(x < 0, -mag, mag)
        )
        return jnp.where(
            jnp.isnan(x), jnp.iinfo(jnp.int64).max, enc
        )
    return data.astype(jnp.int64)


def _collect_float_decode_device(enc: jnp.ndarray) -> jnp.ndarray:
    """Inverse of the float pack, on device (bitcast/ldexp-free):
    value = sign * 2^(e+1) * (0.5 + frac * 2^-53)."""
    mag = jnp.abs(enc)
    e = ((mag >> jnp.int64(52)) - jnp.int64(1100)).astype(jnp.float64)
    frac = (mag & jnp.int64((1 << 52) - 1)).astype(jnp.float64)
    m = 0.5 + frac * float(2.0**-53)
    val = m * jnp.exp2(e + 1.0)
    val = jnp.where(enc < 0, -val, val)
    return jnp.where(enc == 0, 0.0, val)


def _collect_partial_blocks(spec, layout, page, groups, out_cap,
                            collect_k):
    """Partial-step collect state. Null semantics per the reference:
    array_agg INCLUDES null elements (a parallel null-flag matrix rides
    the state); map_agg skips null KEYS but preserves null values;
    approx_percentile ignores nulls. A per-aggregate DISTINCT mask
    always excludes unmarked rows."""
    from presto_tpu.ops import collect as C

    blk = page.block(spec.channel)
    mask = None if spec.mask is None else page.block(spec.mask).data
    contributing = groups.row_valid
    if mask is not None:
        contributing = contributing & mask
    fn = spec.function
    if fn == "map_agg":
        if blk.nulls is not None:  # null keys are skipped
            contributing = contributing & ~blk.nulls
        vblk = page.block(spec.extra_channels[0])
        if vblk.dictionary is not None:
            raise NotImplementedError(
                "map_agg with dictionary-coded (varchar/complex) VALUE "
                "columns is not supported yet; keys may be any type"
            )
        sources = [
            (blk, None),
            (vblk, None),
            (None, vblk.nulls),  # value null flags
        ]
    elif fn == "approx_percentile":
        if blk.nulls is not None:  # percentile ignores nulls
            contributing = contributing & ~blk.nulls
        sources = [(blk, None)]
    else:  # array_agg: null elements included
        sources = [(blk, None), (None, blk.nulls)]
    blocks: List[Block] = []
    overflow = jnp.zeros((), dtype=jnp.bool_)
    for (vb, null_src), st in zip(sources, layout):
        if vb is not None:
            enc = _collect_encode(vb)
            dic = vb.dictionary
        else:
            enc = (null_src.astype(jnp.int64) if null_src is not None
                   else jnp.zeros(page.capacity, dtype=jnp.int64))
            dic = None
        vals, ovf = C.insert(
            groups.group_ids, contributing, out_cap, enc, collect_k
        )
        overflow = overflow | ovf
        blocks.append(Block(data=vals, type=st.type, nulls=None,
                            dictionary=dic))
    cnt, _ = A.aggregate(
        groups, A.COUNT, out_cap,
        jnp.zeros(page.capacity, dtype=jnp.int64),
        ~contributing,
    )
    blocks.append(Block(data=cnt, type=T.BIGINT, nulls=None))
    return blocks, overflow


def _collect_merge_blocks(spec, layout, merged, groups, out_cap, ch,
                          collect_k):
    """Merge partial collect states (grouped by output key): per
    collected column, concatenate member rows' slot vectors in row
    order; the count column segment-sums."""
    from presto_tpu.ops import collect as C

    n_collect = len(layout) - 1
    cnt_blk = merged.block(ch + n_collect)
    counts = cnt_blk.data
    blocks: List[Block] = []
    overflow = jnp.zeros((), dtype=jnp.bool_)
    for i in range(n_collect):
        blk = merged.block(ch + i)
        vals, ovf = C.merge(
            groups.group_ids, groups.row_valid, out_cap,
            blk.data, counts, collect_k,
        )
        overflow = overflow | ovf
        blocks.append(Block(data=vals, type=layout[i].type, nulls=None,
                            dictionary=blk.dictionary))
    ncnt, _ = A.aggregate(groups, A.SUM, out_cap, counts, None)
    blocks.append(Block(data=ncnt, type=T.BIGINT, nulls=None))
    return blocks, overflow


def _collect_finalize_block(spec, in_t, extra_t, state_blocks) -> Block:
    """Merged collect state -> the SQL result Block. The result Block
    carries TUPLE data ((vals2d, nulls2d, counts) for arrays; (k2d,
    v2d, vnulls2d, counts) for maps) decoded host-side at the client
    boundary (page.to_pylist) — collect results cannot feed further
    device expressions (documented divergence; reference arrays are
    first-class)."""
    from presto_tpu.ops import collect as C

    if spec.function == "approx_percentile":
        vals_blk, cnt_blk = state_blocks
        frac = float(spec.params[0]) if spec.params else 0.5
        # the float slot-encoding is order-preserving, so one int64
        # sort serves every element type
        picked = C.percentile_select(
            vals_blk.data, cnt_blk.data, frac,
            vals_blk.data.shape[1],
        )
        if T.is_floating(in_t):
            data = _collect_float_decode_device(picked).astype(
                np.dtype(in_t.numpy_dtype))
        else:
            data = picked.astype(np.dtype(in_t.numpy_dtype))
        return Block(data=data, type=in_t, nulls=cnt_blk.data == 0)
    if spec.function == "map_agg":
        # value columns are restricted to non-dictionary types (checked
        # at partial), so the Block's one dictionary slot carries keys
        k_blk, v_blk, vn_blk, cnt_blk = state_blocks
        out_t = T.MapType(in_t, extra_t[0] if extra_t else T.UNKNOWN)
        return Block(
            data=(k_blk.data, v_blk.data, vn_blk.data, cnt_blk.data),
            type=out_t,
            nulls=cnt_blk.data == 0, dictionary=k_blk.dictionary,
        )
    vals_blk, vn_blk, cnt_blk = state_blocks
    out_t = T.ArrayType(in_t)
    return Block(
        data=(vals_blk.data, vn_blk.data, cnt_blk.data), type=out_t,
        nulls=cnt_blk.data == 0, dictionary=vals_blk.dictionary,
    )


def _partial_agg_page(group_channels, aggregates, layouts, page: Page,
                      cap: int, max_iters: int = 64, collect_k: int = 1024,
                      pallas_agg: bool = False):
    # segmented-reduction Pallas tier (ops/pallas_agg.py, ISSUE 18):
    # same SQL semantics, group totals from the blocked one-hot-matmul
    # kernel; unsupported kinds delegate back to the jnp path inside
    # PA.aggregate, so one dispatch covers the whole layout
    if pallas_agg:
        from presto_tpu.ops import pallas_agg as PA

        agg_fn = functools.partial(PA.aggregate, interpret=True)
    else:
        agg_fn = A.aggregate
    groups = _group_ids(group_channels, page, cap, max_iters)
    # dense fast path may size output below cap (see _group_ids)
    out_cap = groups.group_valid.shape[0]
    keys_page = _agg_keys_page(page, group_channels, groups)
    state_blocks: List[Block] = []
    for spec, layout in zip(aggregates, layouts):
        if spec.function in S.COLLECT_FNS:
            blocks, c_ovf = _collect_partial_blocks(
                spec, layout, page, groups, out_cap, collect_k
            )
            state_blocks.extend(blocks)
            groups.overflow = groups.overflow | c_ovf
            continue
        blk = None if spec.channel is None else page.block(spec.channel)
        blk = _apply_agg_mask(spec, page, blk)
        if spec.function == "approx_distinct":
            words = HLL.insert(
                groups.group_ids, _hll_contributing(groups, blk),
                out_cap, _hll_hashes(blk),
            )
            state_blocks.append(
                Block(data=words, type=T.HLL_STATE, nulls=None)
            )
            continue
        for st in layout:
            vals, out_nulls, dic = _state_reduce(
                st, blk, st.input_kind, True,
                lambda data, nulls, k=st.input_kind: agg_fn(
                    groups, k, out_cap, data, nulls
                ),
            )
            state_blocks.append(
                Block(data=vals, type=st.type, nulls=out_nulls,
                      dictionary=dic)
            )
    out = Page(
        blocks=keys_page.blocks + tuple(state_blocks),
        valid=groups.group_valid,
    )
    return out, groups.overflow


def _merge_partials_page(aggregates, layouts, nkeys, merged: Page,
                         cap: int, max_iters: int = 64,
                         collect_k: int = 1024):
    """Merge partial-state pages into one partial-state page (group by
    keys, merge_kind reductions, NO finalize) — the incremental fold that
    keeps aggregation memory bounded (reference: InMemoryHashAggregation-
    Builder flushing partial results under memory pressure)."""
    key_channels = tuple(range(nkeys))
    groups = _group_ids(key_channels, merged, cap, max_iters)
    out_cap = groups.group_valid.shape[0]
    keys_page = _agg_keys_page(merged, key_channels, groups)
    out_blocks: List[Block] = []
    ch = nkeys
    for spec, layout in zip(aggregates, layouts):
        if spec.function in S.COLLECT_FNS:
            blocks, c_ovf = _collect_merge_blocks(
                spec, layout, merged, groups, out_cap, ch, collect_k
            )
            out_blocks.extend(blocks)
            groups.overflow = groups.overflow | c_ovf
            ch += len(layout)
            continue
        if spec.function == "approx_distinct":
            blk = merged.block(ch)
            ch += 1
            words = HLL.merge(
                groups.group_ids, groups.row_valid, out_cap, blk.data
            )
            out_blocks.append(
                Block(data=words, type=T.HLL_STATE, nulls=None)
            )
            continue
        for st in layout:
            blk = merged.block(ch)
            ch += 1
            vals, out_nulls, dic = _state_reduce(
                st, blk, st.merge_kind, False,
                lambda data, nulls, k=st.merge_kind: A.aggregate(
                    groups, k, out_cap, data, nulls
                ),
            )
            out_blocks.append(
                Block(data=vals, type=st.type, nulls=out_nulls,
                      dictionary=dic)
            )
    out = Page(
        blocks=keys_page.blocks + tuple(out_blocks),
        valid=groups.group_valid,
    )
    return out, groups.overflow


def _final_agg_page(group_channels, aggregates, layouts, in_types,
                    merged: Page, cap: int, max_iters: int = 64,
                    collect_k: int = 1024, extra_types=()):
    nkeys = len(group_channels)
    key_channels = tuple(range(nkeys))
    groups = _group_ids(key_channels, merged, cap, max_iters)
    out_cap = groups.group_valid.shape[0]
    keys_page = _agg_keys_page(merged, key_channels, groups)
    out_blocks: List[Block] = []
    ch = nkeys
    for idx, (spec, layout, in_t) in enumerate(
        zip(aggregates, layouts, in_types)
    ):
        if spec.function in S.COLLECT_FNS:
            blocks, c_ovf = _collect_merge_blocks(
                spec, layout, merged, groups, out_cap, ch, collect_k
            )
            groups.overflow = groups.overflow | c_ovf
            ch += len(layout)
            ext = extra_types[idx] if idx < len(extra_types) else ()
            out_blocks.append(
                _collect_finalize_block(spec, in_t, ext, blocks)
            )
            continue
        if spec.function == "approx_distinct":
            blk = merged.block(ch)
            ch += 1
            words = HLL.merge(
                groups.group_ids, groups.row_valid, out_cap, blk.data
            )
            out_blocks.append(
                Block(data=HLL.estimate(words), type=T.BIGINT,
                      nulls=None)
            )
            continue
        states = []
        state_dic = None
        for st in layout:
            blk = merged.block(ch)
            ch += 1
            vals, out_nulls, dic = _state_reduce(
                st, blk, st.merge_kind, False,
                lambda data, nulls, k=st.merge_kind: A.aggregate(
                    groups, k, out_cap, data, nulls
                ),
            )
            state_dic = state_dic or dic
            states.append((vals, out_nulls))
        out_t = S.result_type(spec.function, in_t)
        out_blocks.append(
            _attach_dictionary(
                S.finalize(spec.function, in_t, out_t, states), state_dic
            )
        )
    out = Page(
        blocks=keys_page.blocks + tuple(out_blocks),
        valid=groups.group_valid,
    )
    return out, groups.overflow


def _partial_global_agg(aggregates, layouts, page: Page) -> Page:
    blocks = []
    for spec, layout in zip(aggregates, layouts):
        blk = None if spec.channel is None else page.block(spec.channel)
        blk = _apply_agg_mask(spec, page, blk)
        if spec.function == "approx_distinct":
            contributing = page.valid
            if blk is not None and blk.nulls is not None:
                contributing = contributing & ~blk.nulls
            words = HLL.global_insert(contributing, _hll_hashes(blk))
            blocks.append(
                Block(data=words, type=T.HLL_STATE, nulls=None)
            )
            continue
        for st in layout:
            vals, is_null, dic = _state_reduce(
                st, blk, st.input_kind, True,
                lambda data, nulls, k=st.input_kind: A.global_aggregate(
                    k, page.valid, data, nulls
                ),
            )
            blocks.append(
                Block(
                    data=vals[None].astype(np.dtype(st.type.numpy_dtype)),
                    type=st.type,
                    nulls=is_null[None],
                    dictionary=dic,
                )
            )
    return Page(blocks=tuple(blocks), valid=jnp.ones((1,), dtype=jnp.bool_))


def _final_global_agg(aggregates, layouts, in_types, merged: Page) -> Page:
    out_blocks = []
    ch = 0
    for spec, layout, in_t in zip(aggregates, layouts, in_types):
        if spec.function == "approx_distinct":
            blk = merged.block(ch)
            ch += 1
            words = HLL.global_merge(merged.valid, blk.data)
            out_blocks.append(
                Block(data=HLL.estimate(words), type=T.BIGINT,
                      nulls=None)
            )
            continue
        states = []
        state_dic = None
        for st in layout:
            blk = merged.block(ch)
            ch += 1
            vals, is_null, dic = _state_reduce(
                st, blk, st.merge_kind, False,
                lambda data, nulls, k=st.merge_kind: A.global_aggregate(
                    k, merged.valid, data, nulls
                ),
            )
            state_dic = state_dic or dic
            states.append((vals[None], is_null[None]))
        out_t = S.result_type(spec.function, in_t)
        out_blocks.append(
            _attach_dictionary(
                S.finalize(spec.function, in_t, out_t, states), state_dic
            )
        )
    return Page(blocks=tuple(out_blocks),
                valid=jnp.ones((1,), dtype=jnp.bool_))


def _empty_state_page(aggregates, layouts, collect_k: int = 1024) -> Page:
    blocks = []
    for spec, layout in zip(aggregates, layouts):
        for st in layout:
            if isinstance(st.type, T.CollectStateType):
                blocks.append(
                    Block(
                        data=jnp.zeros((1, collect_k), dtype=jnp.int64),
                        type=st.type,
                        nulls=None,
                    )
                )
                continue
            if isinstance(st.type, T.HllStateType):
                blocks.append(
                    Block(
                        data=tuple(
                            jnp.zeros((1,), dtype=jnp.int64)
                            for _ in range(HLL.WORDS)
                        ),
                        type=st.type,
                        nulls=None,
                    )
                )
                continue
            blocks.append(
                Block(
                    data=jnp.zeros((1,), dtype=np.dtype(st.type.numpy_dtype)),
                    type=st.type,
                    nulls=jnp.ones((1,), dtype=jnp.bool_),
                )
            )
    return Page(blocks=tuple(blocks), valid=jnp.zeros((1,), dtype=jnp.bool_))


def _empty_page(types: List[T.SqlType], cap: int = 8) -> Page:
    blocks = []
    for t in types:
        if isinstance(t, T.DecimalType) and not t.is_short:
            data = (
                jnp.zeros((cap,), dtype=jnp.int64),
                jnp.zeros((cap,), dtype=jnp.int64),
            )
        else:
            data = jnp.zeros((cap,), dtype=np.dtype(t.numpy_dtype))
        dic = Dictionary([]) if t.is_dictionary_encoded else None
        blocks.append(Block(data=data, type=t, nulls=None, dictionary=dic))
    return Page(blocks=tuple(blocks), valid=jnp.zeros((cap,), dtype=jnp.bool_))


def _null_blocks(types: List[T.SqlType], cap: int) -> List[Block]:
    page = _empty_page(types, cap)
    return [
        Block(
            data=b.data,
            type=b.type,
            nulls=jnp.ones((cap,), dtype=jnp.bool_),
            dictionary=b.dictionary,
        )
        for b in page.blocks
    ]


def _replay_filter(predicate, page: Page) -> Page:
    return evaluate_filter(predicate, page, jnp)


def _node_replay_fn(nd):
    """Per-node page->page replay transform for chain re-execution over
    generated pages (None for pass-through nodes like local Exchange) —
    the ONE place chain-replay semantics live."""
    if isinstance(nd, P.Filter):
        return functools.partial(_replay_filter, nd.predicate)
    if isinstance(nd, P.Project):
        return functools.partial(_project_page, nd.exprs)
    return None


def _subtree_has_join(node: P.PhysicalNode) -> bool:
    if isinstance(node, (P.HashJoin, P.CrossJoin)):
        return True
    return any(_subtree_has_join(c) for c in node.children())


def _filter_chain_has_join(node: P.PhysicalNode) -> bool:
    """Whether a Filter(-over-Filter...) chain sits directly on a
    HashJoin — the shape the lazy-filter driver can stream without
    materializing (projects and blocking ops break the chain)."""
    cur = node
    while isinstance(cur, P.Filter):
        cur = cur.source
    return isinstance(cur, P.HashJoin)


def _fused_agg_step(raw, cap, max_iters, page: Page):
    """Partial-agg tail of a fused pipeline (kernel): distinct groups
    <= rows, so the group capacity clips to the page like the unfused
    driver loop does."""
    return raw(page, min(cap, _next_pow2(page.capacity)), max_iters)


def _fused_merge_step(merge_raw, cap, max_iters, page: Page):
    """State-merge step of the split-batched lax.scan (kernel): fold a
    carry + one split's partial states back into the carry capacity.
    The output capacity is a pure function of (cap, key structure) —
    never of the input page's capacity — so the scan carry keeps one
    static shape whether it was seeded from a lone state page or fed
    the concat of carry + state."""
    return merge_raw(page, cap, max_iters)


def _merge_leading(tree):
    """Collapse the leading batch dim of a stacked Page pytree:
    [B, n, ...] leaves become [B*n, ...] — the in-program equivalent
    of concat_all over the B per-split pages a batched launch covers
    (block metadata is static aux data and survives untouched)."""
    return jax.tree_util.tree_map(
        lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]),
        tree,
    )


def _compact_with_flag(page: Page, cap: int):
    """compact_page plus the dropped-rows overflow flag (kernel)."""
    return (
        compact_page(page, cap),
        page.num_rows() > cap,
    )


def _merge_compact_flag(acc: Page, page: Page, cap: int):
    """Fold one more page into the rolling dense accumulator (kernel):
    concat + stable compaction back to cap, flagging dropped rows."""
    both = concat_all([acc, page])
    return (
        compact_page(both, cap),
        both.num_rows() > cap,
    )


def _generated_join_page(left_key_ch, extra_pairs, join_type, inv, gen,
                         scan_types, scan_dicts, chain_fns, n_rows,
                         page: Page) -> Page:
    """Build-free generated join (kernel): probe keys -> build table
    rows via the connector's closed-form inverse; carried build columns
    GENERATED at those rows; the build side's Filter/Project chain
    replayed over the generated blocks. Pure per-element compute — the
    output page is the probe page extended in place (<=1 match per
    probe row by the key_inverse uniqueness contract), so capacities
    are exact and no overflow flag exists."""
    kblk = page.block(left_key_ch)
    vals = kblk.data.astype(jnp.int64)
    ridx, found = inv(vals)
    if kblk.nulls is not None:
        found = found & ~kblk.nulls
    idx = jnp.clip(ridx, 0, max(n_rows - 1, 0))
    datas, gvalid = gen(idx)
    blocks = tuple(
        Block(data=d, type=t, nulls=None, dictionary=dic)
        for d, t, dic in zip(datas, scan_types, scan_dicts)
    )
    bpage = Page(blocks=blocks, valid=found & gvalid)
    for fn in chain_fns:
        bpage = fn(bpage)
    matched = bpage.valid
    # non-pivot key pairs: equality against the generated build columns
    # (SQL semantics: NULL on either side never matches)
    for lk, rk in extra_pairs:
        lblk, rblk = page.block(lk), bpage.block(rk)
        eq = lblk.data.astype(jnp.int64) == rblk.data.astype(jnp.int64)
        if lblk.nulls is not None:
            eq = eq & ~lblk.nulls
        if rblk.nulls is not None:
            eq = eq & ~rblk.nulls
        matched = matched & eq
    if join_type == "left":
        right_blocks = tuple(
            Block(
                data=b.data, type=b.type,
                nulls=(~matched if b.nulls is None
                       else (b.nulls | ~matched)),
                dictionary=b.dictionary,
            )
            for b in bpage.blocks
        )
        out_valid = page.valid
    else:  # inner
        right_blocks = bpage.blocks
        out_valid = page.valid & matched
    return Page(blocks=page.blocks + right_blocks, valid=out_valid)


def _generated_join_window_page(left_key_ch, extra_pairs, join_type, inv,
                                window, gen_keys, gen, scan_types,
                                scan_dicts, chain_fns, n_rows,
                                page: Page):
    """Windowed generated join (kernel): the pivot key pins an L-slot
    candidate window of the slot-structured build table; the remaining
    key columns are GENERATED at each candidate to resolve the unique
    matching row, then the full carried columns generate at the
    resolved rows — fact⋈fact joins (ss ⋈ sr on ticket+item) with zero
    build state. Returns (page, multi_flag): multi_flag trips when some
    probe row matched >1 candidates (key set not unique in the data) —
    the caller defers it to the overflow ladder, whose retry takes the
    general expanding join."""
    kblk = page.block(left_key_ch)
    vals = kblk.data.astype(jnp.int64)
    base, found = inv(vals)
    if kblk.nulls is not None:
        found = found & ~kblk.nulls
    probe_extras = []
    for lk, _rk in extra_pairs:
        b = page.block(lk)
        if b.nulls is not None:
            found = found & ~b.nulls
        probe_extras.append(b.data.astype(jnp.int64))
    resolved = jnp.zeros_like(vals)
    any_match = jnp.zeros(vals.shape, dtype=jnp.bool_)
    multi = jnp.zeros(vals.shape, dtype=jnp.bool_)
    for k in range(window):
        cand = jnp.clip(base + k, 0, max(n_rows - 1, 0))
        in_range = (base + k >= 0) & (base + k < n_rows)
        kdatas, kvalid = gen_keys(cand)
        mk = found & kvalid & in_range
        for pv, kd in zip(probe_extras, kdatas):
            mk = mk & (pv == kd.astype(jnp.int64))
        multi = multi | (mk & any_match)
        resolved = jnp.where(mk & ~any_match, cand, resolved)
        any_match = any_match | mk
    datas, gvalid = gen(resolved)
    blocks = tuple(
        Block(data=d, type=t, nulls=None, dictionary=dic)
        for d, t, dic in zip(datas, scan_types, scan_dicts)
    )
    bpage = Page(blocks=blocks, valid=any_match & gvalid)
    for fn in chain_fns:
        bpage = fn(bpage)
    matched = bpage.valid
    if join_type == "left":
        right_blocks = tuple(
            Block(
                data=b.data, type=b.type,
                nulls=(~matched if b.nulls is None
                       else (b.nulls | ~matched)),
                dictionary=b.dictionary,
            )
            for b in bpage.blocks
        )
        out_valid = page.valid
    else:  # inner
        right_blocks = bpage.blocks
        out_valid = page.valid & matched
    out = Page(blocks=page.blocks + right_blocks, valid=out_valid)
    return out, jnp.any(multi)


def _build_join_index(left_keys, right_keys, page: Page, build: Page):
    """One-shot build index (kernel). The probe page supplies the static
    dictionary context for canonical key encodings."""
    lblocks = [page.block(c) for c in left_keys]
    rblocks = [build.block(c) for c in right_keys]
    _lcols, _lnulls, rcols, rnulls = _canonical_join_cols(lblocks, rblocks)
    return J.build_join_index(rcols, rnulls, build.valid)


def _probe_join_page(left_keys, right_keys, join_type, defer,
                     page: Page, build: Page, index, out_cap: int):
    lblocks = [page.block(c) for c in left_keys]
    rblocks = [build.block(c) for c in right_keys]
    lcols, lnulls, _rcols, _rnulls = _canonical_join_cols(lblocks, rblocks)
    m = J.hash_join_match(
        None, None, None, lcols, lnulls, page.valid, out_cap, index=index
    )
    return _assemble_join_output(join_type, page, build, m, defer=defer)


def _probe_join_page_unique(left_keys, right_keys, join_type, defer,
                            page: Page, build: Page, index,
                            out_cap: int):
    """FK-join (unique build keys) probe: no match expansion — the
    output page IS the probe page plus gathered build columns; for
    LEFT joins unmatched probe rows simply carry a null build side in
    the SAME page (no appended pad page). out_cap is ignored (output
    capacity == probe capacity by construction).

    defer=True (late materialization): the build side rides as ONE
    int64 row-id column instead of gathered values — and because the
    output rows ARE the probe rows, any indirections the probe page
    already carries pass through with zero gathers."""
    lblocks = [page.block(c) for c in left_keys]
    rblocks = [build.block(c) for c in right_keys]
    lcols, lnulls, _rcols, _rnulls = _canonical_join_cols(lblocks, rblocks)
    bcols, bvalid, sorted_hash, perm = index
    pcols, p_null = J._fold_nulls(lcols, lnulls, False)
    pvalid = page.valid & ~p_null
    phash = H.hash_columns(pcols, [None] * len(pcols))
    lo = jnp.searchsorted(sorted_hash, phash, side="left", method="sort")
    hi = jnp.searchsorted(sorted_hash, phash, side="right",
                          method="sort")
    bid, found, collision = J.unique_join_lookup(
        bcols, bvalid, perm, pcols, pvalid, lo, hi
    )
    # build_matched feeds only RIGHT/FULL outer emission, which this
    # kernel never serves (inner/left only) — a zeros stub keeps the
    # jit output signature without paying the scatter
    matched = jnp.zeros((build.capacity,), dtype=jnp.bool_)
    if defer:
        if join_type == "left":
            id_block = Block(data=bid, type=T.BIGINT, nulls=~found)
            out_valid = page.valid
        else:  # inner
            id_block = Block(data=bid, type=T.BIGINT, nulls=None)
            out_valid = page.valid & found
        out = Page(blocks=page.blocks + (id_block,), valid=out_valid)
        return out, matched, collision
    right_out = gather_rows(build, bid, found)
    if join_type == "left":
        # matched rows carry build values; unmatched carry NULL build
        right_blocks = tuple(
            Block(
                data=b.data, type=b.type,
                nulls=(~found if b.nulls is None else (b.nulls | ~found)),
                dictionary=b.dictionary,
            )
            for b in right_out.blocks
        )
        out_valid = page.valid
    else:  # inner
        right_blocks = right_out.blocks
        out_valid = page.valid & found
    out = Page(blocks=page.blocks + right_blocks, valid=out_valid)
    return out, matched, collision


def _build_radix_join_index(left_keys, right_keys, layout, page: Page,
                            build: Page):
    """Pallas join index (kernel): hash-sorted build order + the
    layout-shaped per-unique-hash (start, count) tables. The probe page
    supplies the static dictionary context, as in _build_join_index."""
    from presto_tpu.ops import pallas_join as PJ

    lblocks = [page.block(c) for c in left_keys]
    rblocks = [build.block(c) for c in right_keys]
    _lcols, _lnulls, rcols, rnulls = _canonical_join_cols(lblocks, rblocks)
    bcols, b_null = J._fold_nulls(rcols, rnulls, False)
    bvalid = build.valid & ~b_null
    bhash = H.hash_columns(bcols, [None] * len(bcols))
    tables, perm, overflow = PJ.build_index(bhash, bvalid, layout)
    return (tuple(bcols), bvalid, perm, tables), overflow


def _probe_radix_join_page(left_keys, right_keys, join_type, layout,
                           interpret, defer, page: Page, build: Page,
                           index, out_cap: int):
    """Probe one page through the Pallas range kernel, then the shared
    verified expansion (J.expand_matches) — identical output contract to
    _probe_join_page; only the range finder differs."""
    from presto_tpu.ops import pallas_join as PJ

    lblocks = [page.block(c) for c in left_keys]
    rblocks = [build.block(c) for c in right_keys]
    lcols, lnulls, _rcols, _rnulls = _canonical_join_cols(lblocks, rblocks)
    bcols, bvalid, perm, tables = index
    pcols, p_null = J._fold_nulls(lcols, lnulls, False)
    pvalid = page.valid & ~p_null
    phash = H.hash_columns(pcols, [None] * len(pcols))
    start, cnt = PJ.probe_index(
        phash, tables, layout, interpret=interpret
    )
    m = J.expand_matches(
        bcols, bvalid, perm, pcols, pvalid,
        jnp.clip(start, 0, None), cnt, out_cap,
    )
    return _assemble_join_output(join_type, page, build, m, defer=defer)


def _assemble_join_output(join_type, page: Page, build: Page,
                          m: J.JoinMatches, defer: bool = False):
    """Expand matches into the output page. defer=True (inner/left
    only) emits ONE int64 build row-id column instead of gathering the
    build blocks — probe columns (including any row-id indirections the
    probe page already carries) gather through probe_idx, which is
    exactly the indirection COMPOSITION of latemat.py."""
    out_valid = m.match
    left_out = gather_rows(page, m.probe_idx, out_valid)
    if defer:
        id_block = Block(
            data=m.build_idx.astype(jnp.int64), type=T.BIGINT,
            nulls=None,
        )
        out = Page(blocks=left_out.blocks + (id_block,),
                   valid=out_valid)
    else:
        right_out = gather_rows(build, m.build_idx, out_valid)
        out = Page(blocks=left_out.blocks + right_out.blocks,
                   valid=out_valid)
    if join_type in ("left", "full"):
        # unmatched probe rows with null build side, appended
        unmatched_valid = page.valid & (m.probe_match_count == 0)
        if defer:
            pad_id = Block(
                data=jnp.zeros((page.capacity,), dtype=jnp.int64),
                type=T.BIGINT,
                nulls=jnp.ones((page.capacity,), dtype=jnp.bool_),
            )
            pad = Page(
                blocks=page.blocks + (pad_id,), valid=unmatched_valid
            )
        else:
            null_right = [
                Block(
                    data=b.data,
                    type=b.type,
                    nulls=jnp.ones((page.capacity,), dtype=jnp.bool_),
                    dictionary=b.dictionary,
                )
                for b in gather_rows(
                    build,
                    jnp.zeros((page.capacity,), dtype=jnp.int64),
                    unmatched_valid,
                ).blocks
            ]
            pad = Page(
                blocks=page.blocks + tuple(null_right),
                valid=unmatched_valid,
            )
        out = concat_all([out, pad])
    return out, m.build_matched, m.overflow


def _unnest_page(array_channel, elem_type, with_ordinality,
                 page: Page) -> Page:
    """Static-shape UNNEST: output capacity = input capacity x L where
    L = max array length over the channel's dictionary (a compile-time
    constant — dictionaries are static aux data). Element values gather
    from a trace-time flat lut; shorter arrays mask out their padding
    (reference: UnnestOperator's per-row element loop, vectorized)."""
    blk = page.block(array_channel)
    dic = blk.dictionary
    vals = [tuple(v) for v in (dic.values if dic is not None else [])]
    n = max(len(vals), 1)
    L = max((len(v) for v in vals), default=0) or 1
    lens = np.zeros((n,), np.int64)
    string_elem = elem_type.is_dictionary_encoded
    if string_elem:
        uniq: dict = {}
        for v in vals:
            for x in v:
                if x is not None:
                    uniq.setdefault(x, len(uniq))
        edic = Dictionary(list(uniq))
        flat = np.zeros((n, L), np.int32)
    else:
        edic = None
        flat = np.zeros((n, L), np.dtype(elem_type.numpy_dtype))
    enull = np.ones((n, L), bool)
    for vi, v in enumerate(vals):
        lens[vi] = len(v)
        for k, x in enumerate(v):
            if x is None:
                continue
            enull[vi, k] = False
            flat[vi, k] = uniq[x] if string_elem else x
    cap = page.capacity
    idx = jnp.arange(cap * L, dtype=jnp.int64)
    i, k = idx // L, idx % L
    codes = jnp.clip(blk.data.astype(jnp.int64), 0, n - 1)[i]
    # xfercheck: raw-ok - trace-time LUT embedding
    valid = page.valid[i] & (k < jnp.asarray(lens)[codes])
    if blk.nulls is not None:
        valid = valid & ~blk.nulls[i]
    src = gather_rows(page, i, valid)
    eblock = Block(
        # xfercheck: raw-ok - trace-time LUT embedding
        data=jnp.asarray(flat)[codes, k],
        type=elem_type,
        # xfercheck: raw-ok - trace-time LUT embedding
        nulls=jnp.asarray(enull)[codes, k],
        dictionary=edic,
    )
    blocks = src.blocks + (eblock,)
    if with_ordinality:
        blocks += (Block(data=k + 1, type=T.BIGINT, nulls=None),)
    return Page(blocks=blocks, valid=valid)


def _group_id_page(key_channels, mask, set_index, page: Page) -> Page:
    """One grouping-set replica: null out keys absent from the set and
    append the constant gid channel."""
    blocks = list(page.blocks)
    for kc, keep in zip(key_channels, mask):
        if not keep:
            b = blocks[kc]
            blocks[kc] = Block(
                data=b.data, type=b.type,
                nulls=jnp.ones((page.capacity,), dtype=jnp.bool_),
                dictionary=b.dictionary,
            )
    gid = Block(
        data=jnp.full((page.capacity,), set_index, dtype=jnp.int64),
        type=T.BIGINT,
    )
    return Page(blocks=tuple(blocks) + (gid,), valid=page.valid)


def _cross_join_page(page: Page, build: Page) -> Page:
    nb = build.capacity
    out_cap = page.capacity * nb
    idx = jnp.arange(out_cap, dtype=jnp.int64)
    li = idx // nb
    ri = idx % nb
    valid = page.valid[li] & build.valid[ri]
    left = gather_rows(page, li, valid)
    right = gather_rows(build, ri, valid)
    return Page(blocks=left.blocks + right.blocks, valid=valid)


def _semi_join_page(left_keys, right_keys, page: Page, build: Page) -> Page:
    lblocks = [page.block(c) for c in left_keys]
    rblocks = [build.block(c) for c in right_keys]
    lcols, lnulls, rcols, rnulls = _canonical_join_cols(lblocks, rblocks)
    has_match, null_result = J.semi_join_mask(
        rcols, rnulls, build.valid, lcols, lnulls, page.valid
    )
    match_block = Block(
        data=has_match, type=T.BOOLEAN, nulls=null_result
    )
    return Page(blocks=page.blocks + (match_block,), valid=page.valid)


def _topn_merge(sort_keys, limit, running: Page, local: Page) -> Page:
    both = concat_all([running, local])
    return sort_page(both, sort_keys=sort_keys, limit=limit)


def _limit_with_count(count, offset, page: Page, consumed):
    """LIMIT across pages with the running total carried as a traced
    device scalar (reference: LimitOperator's remaining counter)."""
    rank = jnp.cumsum(page.valid.astype(jnp.int64)) - 1 + consumed
    keep = page.valid & (rank >= offset) & (rank < offset + count)
    return (
        page.with_valid(keep),
        consumed + jnp.sum(page.valid.astype(jnp.int64)),
    )


def _decode_result_page(page: Page) -> List[tuple]:
    """Decode device rows to Python values, normalizing engine-internal
    encodings (decimal unscaled ints -> Decimal strings stay as ints here;
    clients format)."""
    return page.to_pylist()
