"""Physical plan nodes.

Reference: the operator factories LocalExecutionPlanner wires up —
ScanFilterAndProjectOperator, FilterAndProjectOperator,
HashAggregationOperator, HashBuilderOperator/LookupJoinOperator,
TopNOperator, OrderByOperator, LimitOperator, ValuesOperator,
TaskOutputOperator (presto-main operator/*). A node tree here is what both
hand-built benchmarks (SURVEY §8.1 phase 3) and the SQL planner (phase 4)
emit; the Executor interprets it.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.expr.ir import RowExpression
from presto_tpu.ops.sort import SortKey


class PhysicalNode:
    def children(self) -> Tuple["PhysicalNode", ...]:
        return ()


@dataclasses.dataclass(frozen=True)
class TableScan(PhysicalNode):
    """Leaf: stream pages of selected columns from a connector table
    (reference: operator/TableScanOperator.java + ConnectorPageSource).

    constraint is the pushed-down TupleDomain analog: conjunctive closed
    integer ranges ((column, lo, hi), ...) with None for an open bound —
    advisory split pruning only, the residual Filter above still applies
    (reference: spi/predicate/TupleDomain + ConnectorSplitManager
    pushdown)."""

    catalog: str
    table: str
    columns: Tuple[str, ...]
    constraint: Optional[Tuple[Tuple[str, Optional[int], Optional[int]],
                               ...]] = None


@dataclasses.dataclass(frozen=True)
class Values(PhysicalNode):
    """Inline literal rows (reference: operator/ValuesOperator.java)."""

    types: Tuple[T.SqlType, ...]
    rows: Tuple[tuple, ...]


@dataclasses.dataclass(frozen=True)
class Filter(PhysicalNode):
    source: PhysicalNode
    predicate: RowExpression

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class Project(PhysicalNode):
    """Channel-producing projection (reference: FilterAndProjectOperator's
    project half; exprs reference the source's channels)."""

    source: PhysicalNode
    exprs: Tuple[RowExpression, ...]

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate call (reference: AggregationNode.Aggregation).

    function: sum | count | count_star | min | max | avg | any | bool_or |
    bool_and | the variance family. channel: input channel (None for
    count_star). mask: optional boolean channel — rows where the mask is
    false contribute nothing to THIS aggregate (reference:
    AggregationNode's per-aggregate mask symbol fed by MarkDistinct; the
    mechanism behind mixed DISTINCT aggregates)."""

    function: str
    channel: Optional[int] = None
    mask: Optional[int] = None
    # additional input channels (map_agg's value column) and constant
    # parameters (approx_percentile's fraction) — reference:
    # AggregationNode.Aggregation's full argument list
    extra_channels: Tuple[int, ...] = ()
    params: Tuple = ()


@dataclasses.dataclass(frozen=True)
class MarkDistinct(PhysicalNode):
    """Append one boolean channel per key-set marking the first occurrence
    of each distinct key combination (reference:
    operator/MarkDistinctOperator + plan/MarkDistinctNode). Output
    channels: all source channels, then one mark per entry of
    mark_channel_sets. The TPU shape: group-id computation over the key
    set, then a scatter of True at each group's representative row."""

    source: PhysicalNode
    mark_channel_sets: Tuple[Tuple[int, ...], ...]

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class Aggregation(PhysicalNode):
    """Group-by + aggregates (reference: HashAggregationOperator /
    AggregationOperator for the global case). Output channels: group keys
    first (in group_channels order), then one per AggSpec.

    capacity = max distinct groups the executor sizes for; it retries with
    boosted capacity on overflow (SURVEY §8.2.1 escape hatch).

    step mirrors the reference's AggregationNode.Step: "single" does
    partial+final internally; the distributed fragmenter splits it into
    "partial" (emits accumulator state columns, runs shard-local) and
    "final" (merges state pages after an exchange).
    """

    source: PhysicalNode
    group_channels: Tuple[int, ...]
    aggregates: Tuple[AggSpec, ...]
    capacity: int = 4096
    step: str = "single"

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class HashJoin(PhysicalNode):
    """Equi-join; left = probe side, right = build side (reference:
    LookupJoinOperator probes HashBuilderOperator's LookupSource; the
    planner's AddExchanges decides sides). Output: left channels then right
    channels. join_type: inner | left | right | full | semi | anti.

    For semi/anti the output is the left channels plus one boolean channel
    (match indicator consumed by a downstream filter), mirroring the
    reference's HashSemiJoinOperator emitting a match channel.
    """

    left: PhysicalNode
    right: PhysicalNode
    left_keys: Tuple[int, ...]
    right_keys: Tuple[int, ...]
    join_type: str = "inner"

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class CrossJoin(PhysicalNode):
    """Nested-loop cross product (reference:
    operator/NestedLoopJoinOperator.java). Output left then right channels.
    Only safe when one side is small; the planner uses it as a last resort
    for edge-less join groups."""

    left: PhysicalNode
    right: PhysicalNode

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class UniqueId(PhysicalNode):
    """Append a bigint channel holding a query-unique row id (reference:
    AssignUniqueIdOperator [M]); used by general EXISTS decorrelation."""

    source: PhysicalNode

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class Union(PhysicalNode):
    """UNION ALL: stream children in order (reference: the planner's
    UnionNode collapsing into a shared LocalExchange)."""

    sources: Tuple[PhysicalNode, ...]

    def children(self):
        return self.sources


@dataclasses.dataclass(frozen=True)
class Sort(PhysicalNode):
    source: PhysicalNode
    keys: Tuple[SortKey, ...]

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class TopN(PhysicalNode):
    source: PhysicalNode
    keys: Tuple[SortKey, ...]
    limit: int

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class Limit(PhysicalNode):
    source: PhysicalNode
    count: int
    offset: int = 0

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class Window(PhysicalNode):
    """Window functions sharing one OVER clause (reference:
    sql/planner/plan/WindowNode + operator/WindowOperator). Output
    channels: all source channels, then one per function. Executed as
    segmented scans over a partition-sorted permutation
    (presto_tpu/ops/window.py)."""

    source: PhysicalNode
    partition_channels: Tuple[int, ...]
    order_keys: Tuple[SortKey, ...]
    functions: Tuple  # of ops.window.WindowFunc

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class Exchange(PhysicalNode):
    """Distribution boundary (reference: sql/planner/plan/ExchangeNode
    inserted by AddExchanges; executed by PartitionedOutputOperator /
    ExchangeOperator over HTTP). TPU-native execution maps each kind to an
    XLA collective over the device mesh (SURVEY §3.3):

      repartition -> lax.all_to_all keyed on hash(keys) % n_devices
      broadcast   -> lax.all_gather, every device gets all rows
      gather      -> lax.all_gather to a replicated page (the
                     SINGLE/COORDINATOR_ONLY partitioning analog; downstream
                     single-stream operators run on the replicated copy)
    """

    source: PhysicalNode
    kind: str  # "repartition" | "broadcast" | "gather"
    keys: Tuple[int, ...] = ()

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class Output(PhysicalNode):
    """Terminal: name the output channels (reference: OutputNode +
    TaskOutputOperator)."""

    source: PhysicalNode
    names: Tuple[str, ...]

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class Unnest(PhysicalNode):
    """Expand an array-typed channel: one output row per element, source
    columns replicated (reference: operator/UnnestOperator.java +
    plan/UnnestNode). Static-shape translation: the expansion factor is
    the max array length over the channel's host dictionary (a
    compile-time constant), with a validity mask for shorter arrays."""

    source: PhysicalNode
    array_channel: int
    element_type: T.SqlType
    with_ordinality: bool = False

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class GroupId(PhysicalNode):
    """Grouping-sets expansion (reference: operator/GroupIdOperator.java
    + plan/GroupIdNode): replicate the input once per grouping set,
    nulling out key channels absent from the set, and append a BIGINT
    group-id channel so one aggregation over (keys..., gid) computes
    every set. set_masks[s][k] = key_channels[k] participates in set s."""

    source: PhysicalNode
    key_channels: Tuple[int, ...]
    set_masks: Tuple[Tuple[bool, ...], ...]

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class RemoteSource(PhysicalNode):
    """Pages fetched from remote tasks over the DCN boundary
    (reference: RemoteSourceNode + operator/ExchangeOperator.java).
    The executor resolves ``key`` in its ``remote_sources`` registry to
    a callable yielding deserialized pages. ``origin`` carries the
    remote fragment's root (e.g. the partial-step aggregation) so the
    consuming final step can recover original input types.

    ``est_rows`` is the adaptive-execution stats channel (ISSUE 15):
    when the producing stage has already COMPLETED and spooled, the
    runtime re-planner stamps the exact observed row count here so
    every downstream sizing decision (estimate_rows -> join grace
    partitioning, membudget shares, broadcast flips) runs on measured
    cardinality instead of connector guesses. None = not yet observed
    (estimate from ``origin`` as before). The value itself never
    reaches a jit key — capacities derived from it quantize onto the
    shapes.py ladder first."""

    types: Tuple[T.SqlType, ...]
    key: str
    origin: Optional[PhysicalNode] = None
    est_rows: Optional[int] = None

    def children(self):
        return ()


def channel_width(node: PhysicalNode) -> Optional[int]:
    """Output channel count for the provenance-walkable node shapes
    (scan/filter/project/exchange/limit/inner-join trees)."""
    if isinstance(node, (Filter, Exchange, Limit)):
        return channel_width(node.source)
    if isinstance(node, Project):
        return len(node.exprs)
    if isinstance(node, TableScan):
        return len(node.columns)
    if isinstance(node, HashJoin):
        if node.join_type not in ("inner", "left", "right", "full"):
            return None  # semi/anti output = left + one match channel
        left = channel_width(node.left)
        right = channel_width(node.right)
        if left is None or right is None:
            return None
        return left + right
    return None


def scan_column_of(node: PhysicalNode, ch: int):
    """Provenance of channel ch: the (catalog, table, column) it
    carries unchanged, walked through filters, limits, exchanges,
    identity projections, and join concatenation — None when computed.
    Shared by the DCN hash-repartition planner (which must prove a
    join key IS a table column to co-partition scans on it) and any
    other layout reasoning (reference analog: symbol -> column mapping
    in table layouts)."""
    from presto_tpu.expr.ir import InputRef

    if isinstance(node, (Filter, Exchange, Limit)):
        return scan_column_of(node.source, ch)
    if isinstance(node, Project):
        e = node.exprs[ch]
        if isinstance(e, InputRef):
            return scan_column_of(node.source, e.channel)
        return None
    if isinstance(node, TableScan):
        return (node.catalog, node.table, node.columns[ch])
    if isinstance(node, HashJoin):
        if node.join_type not in ("inner", "left", "right", "full"):
            return None  # semi/anti output = left + one match channel
        # join output = left channels then right channels
        left_w = channel_width(node.left)
        if left_w is None:
            return None
        if ch < left_w:
            return scan_column_of(node.left, ch)
        return scan_column_of(node.right, ch - left_w)
    return None


def scan_column_unique(node: PhysicalNode, ch: int, catalogs) -> bool:
    """Whether channel ch of node provably carries a connector-declared
    unique column, walked through filters, limits, exchanges, and
    identity projections (reference analog: table-layout uniqueness
    constraints). ONE shared walker so the planner's join ordering and
    the executor's join output sizing judge uniqueness identically."""
    from presto_tpu.expr.ir import InputRef

    if isinstance(node, (Filter, Exchange, Limit)):
        return scan_column_unique(node.source, ch, catalogs)
    if isinstance(node, Project):
        e = node.exprs[ch]
        if isinstance(e, InputRef):
            return scan_column_unique(node.source, e.channel, catalogs)
        return False
    if isinstance(node, TableScan):
        conn = catalogs[node.catalog]
        return node.columns[ch] in conn.unique_columns(node.table)
    return False
