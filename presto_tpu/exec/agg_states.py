"""Aggregate state layouts: decompose SQL aggregates into the primitive
segmented reductions of presto_tpu.ops.agg, with exact wide-decimal sums.

Reference: presto-main operator/aggregation/* — each @AggregationFunction
declares state / input / combine / output; e.g. avg = (sum, count) state with
a divide on output, decimal sums carry 128-bit state
(DecimalSumAggregation + UnscaledDecimal128Arithmetic). The TPU translation
of 128-bit state: split each unscaled i64 into (v >> 32, v & 0xFFFFFFFF) and
segment-sum the halves separately — each half-sum stays exact in i64 up to
2^31 rows per group, and hi*2^32 + lo reconstructs the exact 128-bit total,
emitted as a long-decimal limb Block (base-2^64 two's complement).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.ops import agg as A
from presto_tpu.page import Block

# numpy scalars, not jnp: module-level device buffers embedded as jit
# constants permanently degrade the axon TPU runtime (see ops/hashing.py)
_MASK32 = np.int64(0xFFFFFFFF)
_U64_SIGN = np.uint64(0x8000000000000000)


@dataclasses.dataclass(frozen=True)
class StateCol:
    """One physical state column: which primitive reduction builds it from
    raw input, and which merges two partial states of it."""

    suffix: str
    input_kind: str  # ops.agg kind applied to raw input
    merge_kind: str  # ops.agg kind applied when combining partials
    type: T.SqlType
    # transform applied to the raw input column before reduction
    pre: Optional[str] = None  # None | 'hi32' | 'lo32'


# collect-state aggregate markers (handled by the executor's collect
# branches against ops/collect.py, never by ops/agg.aggregate)
COLLECT = "collect"
COLLECT_FNS = frozenset({"array_agg", "map_agg", "approx_percentile"})


def state_layout(function: str, in_type: Optional[T.SqlType]) -> List[StateCol]:
    """State columns for an aggregate over an input type (reference analog:
    the generated GroupedAccumulator field layout)."""
    if function == "count_star":
        return [StateCol("count", A.COUNT_STAR, A.SUM, T.BIGINT)]
    if function == "count":
        return [StateCol("count", A.COUNT, A.SUM, T.BIGINT)]
    if function in ("min", "max"):
        kind = A.MIN if function == "min" else A.MAX
        return [StateCol("value", kind, kind, in_type)]
    if function == "any":
        return [StateCol("value", A.ANY, A.ANY, in_type)]
    if function == "bool_or":
        return [StateCol("value", A.BOOL_OR, A.BOOL_OR, T.BOOLEAN)]
    if function == "bool_and":
        return [StateCol("value", A.BOOL_AND, A.BOOL_AND, T.BOOLEAN)]
    if function == "sum":
        if isinstance(in_type, T.DecimalType):
            return [
                StateCol("hi", A.SUM, A.SUM, T.BIGINT, pre="hi32"),
                StateCol("lo", A.SUM, A.SUM, T.BIGINT, pre="lo32"),
            ]
        if T.is_floating(in_type):
            return [StateCol("sum", A.SUM, A.SUM, T.DOUBLE)]
        return [StateCol("sum", A.SUM, A.SUM, T.BIGINT)]
    if function == "avg":
        return state_layout("sum", in_type) + state_layout("count", in_type)
    if function in VARIANCE_FNS:
        # (count, sum, sum-of-squares) double state; the planner casts the
        # input to DOUBLE first. Reference: operator/aggregation/
        # VarianceAggregation uses (count, mean, m2) Welford state — the
        # TPU translation uses moment sums because they are plain segmented
        # reductions (merge = add); m2 is recovered at finalize.
        return [
            StateCol("count", A.COUNT, A.SUM, T.BIGINT),
            StateCol("sum", A.SUM, A.SUM, T.DOUBLE),
            StateCol("sumsq", A.SUM, A.SUM, T.DOUBLE, pre="sq"),
        ]
    if function == "approx_distinct":
        # one tuple-data state column of packed HLL register words;
        # insert/merge/estimate are special-cased in the executor
        # kernels (exec/executor.py) against ops/hll.py. Reference:
        # operator/aggregation/ApproximateCountDistinctAggregation.
        return [StateCol("hll", A.HLL_INSERT, A.HLL_MERGE, T.HLL_STATE)]
    if function == "approx_percentile":
        # [cap, K] collected-value matrix + used-slot count;
        # insert/merge special-cased in the executor kernels against
        # ops/collect.py (reference: ApproximatePercentileAggregations;
        # ours is EXACT within the array_agg_max_elements bound).
        return [
            StateCol("vals", COLLECT, COLLECT, T.CollectStateType(
                in_type if in_type is not None else T.UNKNOWN)),
            StateCol("count", A.COUNT, A.SUM, T.BIGINT),
        ]
    if function == "array_agg":
        # value matrix + element-null-flag matrix + used-slot count
        # (reference: ArrayAggregationFunction — null elements are
        # INCLUDED in the collected array)
        return [
            StateCol("vals", COLLECT, COLLECT, T.CollectStateType(
                in_type if in_type is not None else T.UNKNOWN)),
            StateCol("vnulls", COLLECT, COLLECT,
                     T.CollectStateType(T.UNKNOWN)),
            StateCol("count", A.COUNT, A.SUM, T.BIGINT),
        ]
    if function == "map_agg":
        # collected keys + values + value-null flags + count
        # (reference: MapAggregationFunction's KeyValuePairsState —
        # null keys skipped, null values preserved)
        return [
            StateCol("kvals", COLLECT, COLLECT, T.CollectStateType(
                in_type if in_type is not None else T.UNKNOWN)),
            StateCol("vvals", COLLECT, COLLECT,
                     T.CollectStateType(T.UNKNOWN)),
            StateCol("vnulls", COLLECT, COLLECT,
                     T.CollectStateType(T.UNKNOWN)),
            StateCol("count", A.COUNT, A.SUM, T.BIGINT),
        ]
    if function in _PLUGIN_AGGS:
        return list(_PLUGIN_AGGS[function].state)
    raise ValueError(f"unknown aggregate function: {function}")


VARIANCE_FNS = frozenset(
    {"var_samp", "var_pop", "stddev_samp", "stddev_pop"}
)


@dataclasses.dataclass(frozen=True)
class AggregateFunctionSpec:
    """Plugin aggregate (reference: @AggregationFunction state/input/
    combine/output; spi/Plugin.getFunctions). The TPU decomposition:
    ``state`` columns are built from the primitive segmented-reduction
    kinds of ops/agg (input_kind on raw input, merge_kind on partial
    states — so PARTIAL/FINAL splits, spill partitions, and mesh
    repartition all work unchanged), and ``finalize(xp, states)``
    combines the merged state arrays into ``(data, nulls-or-None)``.

    ``StateCol.pre`` may be a module-level callable (traced transform
    applied to the raw input before reduction); lambdas would defeat
    the jit cache keying, so use named functions."""

    name: str
    state: Tuple[StateCol, ...]
    result: object  # SqlType, or callable(in_type) -> SqlType
    finalize: object  # fn(xp, states) -> (data, nulls or None)


_PLUGIN_AGGS: dict = {}


def register_aggregate(spec: AggregateFunctionSpec) -> None:
    _PLUGIN_AGGS[spec.name] = spec


def is_plugin_aggregate(name: str) -> bool:
    return name in _PLUGIN_AGGS


def result_type(
    function: str,
    in_type: Optional[T.SqlType],
    extra: tuple = (),
) -> T.SqlType:
    """Reference: FunctionRegistry aggregate signatures — sum(bigint)->
    bigint, sum(decimal(p,s))->decimal(38,s), avg(decimal(p,s))->
    decimal(p,s), count->bigint. ``extra`` carries additional input
    types (map_agg's value column)."""
    if function in ("count", "count_star"):
        return T.BIGINT
    if function == "array_agg":
        return T.ArrayType(in_type if in_type is not None else T.UNKNOWN)
    if function == "map_agg":
        return T.MapType(
            in_type if in_type is not None else T.UNKNOWN,
            extra[0] if extra else T.UNKNOWN,
        )
    if function == "approx_percentile":
        return in_type
    if function in ("min", "max", "any"):
        return in_type
    if function in ("bool_or", "bool_and"):
        return T.BOOLEAN
    if function == "sum":
        if isinstance(in_type, T.DecimalType):
            return T.DecimalType(38, in_type.scale)
        if T.is_floating(in_type):
            return T.DOUBLE
        return T.BIGINT
    if function == "avg":
        if isinstance(in_type, T.DecimalType):
            return in_type
        return T.DOUBLE
    if function in VARIANCE_FNS:
        return T.DOUBLE
    if function == "approx_distinct":
        return T.BIGINT
    if function in _PLUGIN_AGGS:
        r = _PLUGIN_AGGS[function].result
        return r(in_type) if callable(r) else r
    raise ValueError(f"unknown aggregate function: {function}")


def pre_transform(pre, data: jnp.ndarray) -> jnp.ndarray:
    if pre is None:
        return data
    if callable(pre):  # plugin aggregates: named traced transform
        return pre(data)
    if pre == "hi32":
        return data >> jnp.int64(32)  # arithmetic: floor(v / 2^32)
    if pre == "lo32":
        return data & _MASK32
    if pre == "sq":
        d = data.astype(jnp.float64)
        return d * d
    raise ValueError(pre)


def split32_to_limbs(hi: jnp.ndarray, lo: jnp.ndarray):
    """(sum of v>>32, sum of v&0xFFFFFFFF) -> base-2^64 two's-complement
    limbs of the exact 128-bit value hi*2^32 + lo."""
    u_shift = hi.astype(jnp.uint64) << jnp.uint64(32)
    u_lo = lo.astype(jnp.uint64)
    lo64 = u_shift + u_lo
    carry = (lo64 < u_shift).astype(jnp.int64)
    hi64 = (hi >> jnp.int64(32)) + carry
    return hi64, lo64.astype(jnp.int64)


def finalize(
    function: str,
    in_type: Optional[T.SqlType],
    out_type: T.SqlType,
    states: List[Tuple[jnp.ndarray, Optional[jnp.ndarray]]],
    xp=jnp,
) -> Block:
    """Combine merged state columns into the SQL result Block."""
    if function in ("count", "count_star"):
        data, _ = states[0]
        return Block(data=data, type=T.BIGINT, nulls=None)
    if function in ("min", "max", "any", "bool_or", "bool_and"):
        data, nulls = states[0]
        return Block(data=data, type=out_type, nulls=nulls)
    if function == "sum":
        if isinstance(in_type, T.DecimalType):
            (hi, hn), (lo, _) = states
            hi64, lo64 = split32_to_limbs(hi, lo)
            return Block(data=(hi64, lo64), type=out_type, nulls=hn)
        data, nulls = states[0]
        return Block(data=data, type=out_type, nulls=nulls)
    if function == "avg":
        if isinstance(in_type, T.DecimalType):
            (hi, hn), (lo, _), (count, _) = states
            cnt = xp.maximum(count, jnp.int64(1))
            # exact two-step 128/64 divide with round-half-up; derivation
            # assumes the non-negative domain (money sums); negative totals
            # fall back through the same path with floor bias ≤ 1 ulp.
            # lo is a segment-sum of 32-bit halves (up to n*2^32 for an
            # n-row group), so fold its high half into the 2^32-weighted
            # dividend first — keeps rest < (n+1)*2^32, in-range through
            # the documented 2^31-rows-per-group bound.
            hi2 = hi + (lo >> jnp.int64(32))
            lo_low = lo & jnp.int64(0xFFFFFFFF)
            qh = hi2 // cnt
            rh = hi2 - qh * cnt
            rest = (rh << jnp.int64(32)) + lo_low
            q2 = (rest + cnt // jnp.int64(2)) // cnt
            avg = (qh << jnp.int64(32)) + q2
            return Block(data=avg, type=out_type, nulls=hn)
        (s, sn), (count, _) = states
        cnt = xp.maximum(count, jnp.int64(1)).astype(jnp.float64)
        data = s.astype(jnp.float64) / cnt
        return Block(data=data, type=T.DOUBLE, nulls=sn)
    if function in VARIANCE_FNS:
        (count, _), (s, _), (sq, _) = states
        n = count.astype(jnp.float64)
        safe_n = xp.maximum(n, 1.0)
        s = s.astype(jnp.float64)
        # m2 = sum((x - mean)^2) = sumsq - sum^2/n; clamp the cancellation
        # residue so rounding never yields a negative variance / NaN sqrt
        m2 = xp.maximum(sq.astype(jnp.float64) - s * s / safe_n, 0.0)
        if function.endswith("_pop"):
            var = m2 / safe_n
            nulls = count == 0
        else:
            var = m2 / xp.maximum(n - 1.0, 1.0)
            nulls = count < 2
        if function.startswith("stddev"):
            var = xp.sqrt(var)
        return Block(data=var, type=T.DOUBLE, nulls=nulls)
    if function in _PLUGIN_AGGS:
        data, nulls = _PLUGIN_AGGS[function].finalize(xp, states)
        return Block(data=data, type=out_type, nulls=nulls)
    raise ValueError(f"unknown aggregate function: {function}")
