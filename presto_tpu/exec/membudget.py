"""Device-memory governor: plan-time HBM budget accounting.

Reference: presto-main's memory/MemoryPool + LocalMemoryContext
hierarchy and the spill decisions AddLocalExchanges/spiller make under
memory pressure. The reference REACTS to allocation (revocable memory,
spill-on-pressure); the TPU translation can do better: every buffer
capacity the executor allocates quantizes onto the exec/shapes.py
ladder BEFORE compile, so a pipeline's peak live device bytes is a
static function of the plan — computable, checkable, and fixable
(by chunked rewrites) before a single program launches.

The model (ROOFLINE.md §8):

    bytes(buffer)   = bucket(rows) * row_bytes        (the allocation)
    bytes(pipeline) = sum of concurrently-live buffer footprints
    chunks          = ceil(peak / budget-share)

Governed decisions, each a *chunked rewrite* of the pipeline rather
than a failure:

  - join builds:   grace-partition passes sized to fit (parts_for)
  - join outputs:  probe pages position-chunked so output capacity
                   stays under its share
  - scans:         generation chunk (page) size shrunk to fit — a
                   Q1/Q6-shaped pipeline streams an arbitrarily large
                   table through fixed-size resident buffers
  - aggregations:  hash-partition passes when state exceeds its share
  - intermediates: PageStore host/disk tiers engage when a
                   materialization exceeds its share

The budget itself: session property `device_memory_budget` (bytes;
0 = auto). Auto resolves to the device's real HBM minus headroom on
TPU and a generous cap on CPU (tier-1 tests see no behavior change
unless they force a tiny budget).

Shares: one pipeline holds several live buffers at once (build +
probe page + output page + downstream materialization), so no single
buffer may claim the whole budget. The divisors are deliberately
coarse powers of two — the ladder absorbs the slack.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from presto_tpu.exec import shapes as SH

# Fallback HBM size when the runtime exposes no memory_stats (v5e:
# 16 GiB per chip — the bench target in BASELINE.md).
DEFAULT_TPU_HBM = 16 << 30

# Fraction of HBM held back from the governor: runtime scratch,
# compiled-program buffers, XLA temp allocations. budget = HBM * 7/8.
HEADROOM_DIV = 8

# CPU "budget": effectively unbounded for tier-1 scale, small enough
# that a genuinely absurd plan still trips the audit. 16 GiB.
CPU_BUDGET = 1 << 34

# Budget shares (divisors of the resolved budget):
#   join build materialization / aggregation state / sort-window merge
BUILD_SHARE_DIV = 4
#   a single join-output or landing page
PAGE_SHARE_DIV = 8
#   one scan generation buffer (many are live across a fused batch)
SCAN_SHARE_DIV = 8
#   a restreamable intermediate staying device-resident (PageStore)
STORE_SHARE_DIV = 2


def device_hbm_bytes() -> Optional[int]:
    """Physical device memory of the default backend's first device,
    None when the runtime does not expose it (CPU, some TPU stacks)."""
    import jax

    try:
        dev = jax.local_devices()[0]
        stats = dev.memory_stats()
        if stats:
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit"
            )
            if limit:
                return int(limit)
    except Exception:  # noqa: BLE001 - memory_stats is an optional
        pass           # backend API; absence means "unknown HBM"
    return None


def resolve_budget(setting: int, backend: Optional[str] = None) -> int:
    """device_memory_budget resolution: an explicit positive setting
    wins; 0 (auto) = real HBM minus headroom on TPU, the generous
    CPU_BUDGET elsewhere."""
    if setting and int(setting) > 0:
        return int(setting)
    if backend is None:
        import jax

        backend = jax.default_backend()
    if backend != "tpu":
        return CPU_BUDGET
    hbm = device_hbm_bytes() or DEFAULT_TPU_HBM
    return hbm - hbm // HEADROOM_DIV


def group_share_bytes(share: float, setting: int = 0,
                      backend: Optional[str] = None) -> int:
    """Resolve a resource group's fractional HBM ``memory_share``
    (server/resource_groups.py, ISSUE 17) into the governed
    device_memory_budget for ONE admitted query: the share of the
    resolved whole-device budget, floored so a tiny share still
    leaves the governor a workable chunk size (it rewrites pipelines
    to fit rather than failing them). 0 when no share is configured —
    the session/default budget applies unchanged."""
    if share <= 0:
        return 0
    total = resolve_budget(setting, backend)
    return max(int(total * share), 1 << 24)


def rows_cap(row_bytes: int, budget: int, fault_rows: Optional[int],
             share_div: int) -> Optional[int]:
    """Largest governed buffer capacity (in rows, on the ladder) for a
    buffer of `row_bytes`-wide rows claiming budget/share_div bytes,
    additionally under the device fault line when one applies.
    None = unconstrained (no budget, no fault line)."""
    caps = []
    if budget:
        share = budget // share_div
        caps.append(max(share // max(int(row_bytes), 1), SH.LADDER_MIN))
    if fault_rows:
        caps.append(int(fault_rows))
    if not caps:
        return None
    cap = min(caps)
    # round DOWN to the ladder (bucket rounds up; a cap must not)
    b = SH.bucket(cap)
    return b if b <= cap else b >> 1


# ------------------------------------------------------------- audit
@dataclasses.dataclass
class BufferPlan:
    """One planned device buffer: what the executor will allocate for
    this node under the current session, per the shared sizing model."""

    label: str
    rows: int          # ladder-bucketed capacity
    row_bytes: int
    chunked: bool = False   # a governed rewrite resized/partitioned it
    # buffer donation (ISSUE 13): the executor's merge-accumulator
    # programs take this buffer via donate_argnums, so the merge input
    # and output SHARE one allocation — the donated input must not
    # double-count against the concurrent-footprint model
    donated: bool = False

    @property
    def bytes(self) -> int:
        return self.rows * self.row_bytes

    @property
    def live_bytes(self) -> int:
        """Contribution to the concurrent-footprint model: a donated
        accumulator holds ONE allocation across the merge chain (the
        in-place reuse donate_argnums buys), where the non-donated
        path holds the dying input alongside the fresh output — half
        the undonated model's 2x charge."""
        return self.bytes // 2 if self.donated else self.bytes


@dataclasses.dataclass
class AuditReport:
    budget: int
    fault_rows: Optional[int]
    buffers: List[BufferPlan]

    @property
    def peak_bytes(self) -> int:
        """Model pipeline peak: the sum of the two largest concurrent
        buffers plus one page share — a deliberate over- rather than
        under-estimate (streaming keeps most buffers dead). Donated
        accumulators count at live_bytes (in-place reuse)."""
        sizes = sorted((b.live_bytes for b in self.buffers),
                       reverse=True)
        return sum(sizes[:2]) + (sizes[2] if len(sizes) > 2 else 0) // 2

    @property
    def max_buffer_bytes(self) -> int:
        return max((b.bytes for b in self.buffers), default=0)

    def over_fault_line(self) -> List[BufferPlan]:
        """Buffers planned STRICTLY past the governed row ceiling — a
        buffer sized exactly at the cap is the governor doing its job
        (the real device fault line sits a ladder rung above it)."""
        if not self.fault_rows:
            return []
        return [b for b in self.buffers if b.rows > self.fault_rows]

    def over_budget(self) -> List[BufferPlan]:
        return [b for b in self.buffers if b.bytes > self.budget]

    @property
    def ok(self) -> bool:
        return not self.over_fault_line() and not self.over_budget()

    @property
    def chunked_count(self) -> int:
        return sum(1 for b in self.buffers if b.chunked)


def audit(ex, node) -> AuditReport:
    """Static per-plan footprint prediction: walk the physical plan
    recording every device buffer the executor WILL allocate under its
    current knobs — the same sizing functions the streaming paths call,
    so the prediction and the execution cannot drift apart. No pages
    are generated and nothing touches the device."""
    from presto_tpu.exec import plan as P
    from presto_tpu.exec.executor import _row_bytes

    budget = ex._budget()
    fault = ex._fault_rows()
    donate = ex._donate_on()
    buffers: List[BufferPlan] = []

    def add(label, rows, row_b, chunked=False, donated=False):
        buffers.append(BufferPlan(label, SH.bucket(rows), max(row_b, 1),
                                  chunked=chunked,
                                  donated=donated and donate))

    def emit_cap(n) -> Optional[int]:
        """Upper bound on the page capacity a subtree can EMIT — the
        executor's own clamps, which a raw cardinality estimate does
        not know about (a blocking sort above an aggregation merges the
        aggregation's clamped output, not the fact table)."""
        if isinstance(n, (P.Filter, P.Project, P.Exchange, P.Limit,
                          P.Output)):
            src = emit_cap(n.source)
            if isinstance(n, P.Limit):
                lim = SH.bucket(max(n.count + n.offset, 8))
                return lim if src is None else min(src, lim)
            return src
        if isinstance(n, P.Aggregation):
            if not n.group_channels:
                return SH.LADDER_MIN
            cap = SH.bucket(max(n.capacity, 8))
            if ex.agg_optimistic_rows:
                cap = min(cap, SH.bucket(ex.agg_optimistic_rows))
            return cap
        if isinstance(n, P.TopN):
            return SH.bucket(max(n.limit, 8))
        return None

    # (TopN running-merge buffers are donated too — executor
    # topn_merge site — but TopN never reaches add(): its candidate
    # set is bounded by the limit bucket, noise next to real buffers)

    def walk(n):
        if isinstance(n, P.TableScan):
            types = ex.output_types(n)
            row_b = _row_bytes(types)
            target = ex._governed_target_rows(types, count=False)
            add(f"scan {n.table} page", target, row_b,
                chunked=target < ex.page_rows)
            return
        if isinstance(n, P.HashJoin):
            left_types = ex.output_types(n.left)
            right_types = ex.output_types(n.right)
            gj = ex._generated_join_info(n, left_types)
            if gj is not None:
                # build-free: zero join state — but the fused chain's
                # page carries left+right columns per slot, and the
                # governor chunks generation by that WIDEST width
                out_types = ex.output_types(n)
                out_row_b = _row_bytes(out_types)
                target = ex._governed_target_rows(
                    out_types, count=False, row_bytes=out_row_b
                )
                add(f"genjoin chain page ({n.join_type})", target,
                    out_row_b, chunked=target < ex.page_rows)
                walk(n.left)
                return
            row_b = _row_bytes(right_types)
            est_build = ex.estimate_rows(n.right)
            parts, governed = ex._join_parts(
                n, left_types, right_types, est_build, row_b
            )
            if parts == 1:
                per_pass = SH.bucket(est_build)
            else:
                # per-pass chunks carry 2x slack over 1/parts occupancy
                # (the same factor _join_parts governs for)
                per_pass = -(-SH.bucket(est_build) * 2 // parts)
            add(
                f"join build {n.join_type} (1/{parts} pass)",
                per_pass, row_b, chunked=governed,
            )
            out_row_b = row_b + _row_bytes(left_types)
            oc_cap = rows_cap(out_row_b, budget, fault, PAGE_SHARE_DIV)
            probe_rows = min(
                ex.page_rows, SH.bucket(ex.estimate_rows(n.left))
            )
            oc = SH.bucket(
                min(max(probe_rows * 2, 8192),
                    max(4 * ex.page_rows, 1 << 19))
            )
            add(
                f"join output {n.join_type}",
                min(oc, oc_cap) if oc_cap else oc, out_row_b,
                chunked=bool(oc_cap and oc > oc_cap),
            )
            walk(n.left)
            walk(n.right)
            return
        if isinstance(n, P.Aggregation):
            types = ex.output_types(n)
            row_b = _row_bytes(types)
            if not n.group_channels:
                add("global agg state", SH.LADDER_MIN, row_b)
            else:
                cap = SH.bucket(max(n.capacity, 8))
                if ex.agg_optimistic_rows:
                    cap = min(cap, SH.bucket(ex.agg_optimistic_rows))
                # row ceiling = the executor's governed FOLD cap
                # (fr>>2), the largest state the single path can hold
                state_cap = rows_cap(
                    row_b, budget,
                    fault and max(fault >> 2, 8192),
                    BUILD_SHARE_DIV,
                )
                # the fold accumulator is a donated merge input when
                # buffer donation is on — the chained merges reuse
                # one allocation in place (executor agg_merge sites)
                add("agg state", min(cap, state_cap) if state_cap
                    else cap, row_b,
                    chunked=bool(state_cap and cap > state_cap),
                    donated=True)
            walk(n.source)
            return
        if isinstance(n, (P.Sort, P.Window, P.MarkDistinct)):
            # blocking whole-input merge: no chunked rewrite exists for
            # these yet — the audit REPORTS them so an over-line plan
            # fails loudly before the device faults. The estimate is
            # bounded by what the source can actually emit (an
            # aggregation's clamped output, a TopN's limit).
            types = ex.output_types(n)
            est = ex.estimate_rows(n)
            cap = emit_cap(n.source)
            if cap is not None:
                est = min(est, cap)
            add(f"{type(n).__name__.lower()} merge", est,
                _row_bytes(types))
            walk(n.source)
            return
        if isinstance(n, P.CrossJoin):
            add("cross build", 4096, _row_bytes(
                ex.output_types(n.right)))
            walk(n.left)
            walk(n.right)
            return
        for c in n.children():
            walk(c)

    walk(node)
    return AuditReport(budget=budget, fault_rows=fault, buffers=buffers)


def render(report: AuditReport) -> str:
    lines = [
        f"budget {report.budget / 1e6:.1f} MB, fault line "
        f"{report.fault_rows or '—'} rows; model peak "
        f"{report.peak_bytes / 1e6:.2f} MB; "
        f"{report.chunked_count} governed rewrites"
    ]
    over_line = set(map(id, report.over_fault_line()))
    for b in sorted(report.buffers, key=lambda x: -x.bytes):
        flag = ""
        if id(b) in over_line:
            flag = "  ** OVER FAULT LINE **"
        elif b.bytes > report.budget:
            flag = "  ** OVER BUDGET **"
        elif b.chunked:
            flag = "  [chunked]"
        elif b.donated:
            flag = "  [donated]"
        lines.append(
            f"  {b.label:<38} {b.rows:>10} rows x {b.row_bytes:>4} B "
            f"= {b.bytes / 1e6:>10.2f} MB{flag}"
        )
    return "\n".join(lines)
