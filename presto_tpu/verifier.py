"""Result checksum utilities.

Reference: presto-verifier — replays query suites against two clusters and
compares row counts + ORDER-INSENSITIVE checksums (aggregate over per-row
hashes) rather than sorted row lists. Ours is the same idea for
single-vs-distributed and engine-vs-oracle comparisons:

    checksum = sum(row_hash(row)) mod 2^64
    row_hash = 31*h + column_hash chain (CombineHashFunction), with
    xxhash64 per column value — bit-compatible with ops/hashing.py's
    device-side kernels so a device-computed checksum can be compared
    against a host-computed one.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

_MASK = (1 << 64) - 1

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK


def xxhash64_long(value: int, seed: int = 0) -> int:
    """xxhash64 of one 8-byte little-endian value; bit-exact with
    io.airlift.slice.XxHash64.hash(long) and ops/hashing.xxhash64_u64."""
    v = value & _MASK
    acc = (seed + _P5 + 8) & _MASK
    k1 = (v * _P2) & _MASK
    k1 = _rotl(k1, 31)
    k1 = (k1 * _P1) & _MASK
    acc ^= k1
    acc = (_rotl(acc, 27) * _P1 + _P4) & _MASK
    acc ^= acc >> 33
    acc = (acc * _P2) & _MASK
    acc ^= acc >> 29
    acc = (acc * _P3) & _MASK
    acc ^= acc >> 32
    return acc


def _value_hash(v) -> int:
    """Per-type canonical hash. NULL -> 0 (reference:
    TypeUtils.hashPosition's NULL_HASH_CODE)."""
    if v is None:
        return 0
    if isinstance(v, bool):
        return xxhash64_long(1 if v else 0)
    if isinstance(v, int):
        return xxhash64_long(v)
    if isinstance(v, float):
        # canonicalize to the double's bit pattern (NaNs normalized)
        import math
        import struct

        if math.isnan(v):
            bits = 0x7FF8000000000000
        else:
            bits = struct.unpack("<q", struct.pack("<d", v))[0]
        return xxhash64_long(bits)
    if isinstance(v, str):
        # chain of char-code hashes (strings are dictionary-coded on
        # device; host side hashes the decoded value canonically)
        h = 0
        for b in v.encode("utf-8"):
            h = (h * 31 + b) & _MASK
        return xxhash64_long(h)
    raise TypeError(f"unhashable result value: {v!r} ({type(v)})")


def row_hash(row: Iterable) -> int:
    """Reference: CombineHashFunction.getHash: h = 31*h + col_hash."""
    h = 0
    for v in row:
        h = (h * 31 + _value_hash(v)) & _MASK
    return h


def checksum_rows(rows: List[tuple]) -> dict:
    """Order-insensitive result digest (verifier-style)."""
    total = 0
    for r in rows:
        total = (total + row_hash(r)) & _MASK
    return {"count": len(rows), "checksum": total}


def assert_same_results(
    a: List[tuple], b: List[tuple], label: str = ""
) -> None:
    ca, cb = checksum_rows(a), checksum_rows(b)
    assert ca["count"] == cb["count"], (
        f"{label}: row count {ca['count']} != {cb['count']}"
    )
    assert ca["checksum"] == cb["checksum"], (
        f"{label}: checksums differ over {ca['count']} rows "
        f"({ca['checksum']:#x} vs {cb['checksum']:#x})\n"
        f"a head: {a[:3]}\nb head: {b[:3]}"
    )
