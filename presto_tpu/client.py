"""Client for the /v1/statement protocol.

Reference: presto-client client/StatementClient.java — POST the SQL, then
advance nextUri until it disappears, accumulating typed rows; honor
X-Presto-Set-Session responses by carrying the property forward on later
requests (sessions are client-held, the server is stateless).
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.request
from typing import Dict, List, Optional


@dataclasses.dataclass
class ClientResult:
    columns: List[Dict]
    rows: List[list]
    state: str
    query_id: str
    update_type: Optional[str] = None
    error: Optional[Dict] = None


class StatementClient:
    def __init__(
        self,
        server: str = "http://127.0.0.1:8080",
        user: str = "presto",
        catalog: Optional[str] = None,
        schema: str = "default",
        timeout: float = 3600.0,
    ):
        self.server = server.rstrip("/")
        self.user = user
        self.catalog = catalog
        self.schema = schema
        self.timeout = timeout
        self.session_properties: Dict[str, str] = {}

    def _headers(self) -> Dict[str, str]:
        h = {
            "X-Presto-User": self.user,
            "X-Presto-Schema": self.schema,
            "Content-Type": "text/plain",
        }
        if self.catalog:
            h["X-Presto-Catalog"] = self.catalog
        if self.session_properties:
            h["X-Presto-Session"] = ",".join(
                f"{k}={v}" for k, v in self.session_properties.items()
            )
        return h

    def _request(self, url: str, data: Optional[bytes] = None,
                 method: str = "GET"):
        req = urllib.request.Request(
            url, data=data, headers=self._headers(), method=method
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                body = json.loads(resp.read().decode())
                set_sess = resp.headers.get("X-Presto-Set-Session")
                if set_sess and "=" in set_sess:
                    k, v = set_sess.split("=", 1)
                    self.session_properties[k] = v
        except urllib.error.HTTPError as e:
            # error statuses (e.g. 429 QUERY_QUEUE_FULL) still carry the
            # protocol's error body — surface it instead of raising
            # (reference: StatementClient parses QueryResults.error)
            try:
                body = json.loads(e.read().decode())
            except Exception:
                raise e
            if "error" not in body:
                raise e
        return body

    def execute(self, sql: str) -> ClientResult:
        deadline = time.time() + self.timeout
        body = self._request(
            f"{self.server}/v1/statement", sql.encode(), "POST"
        )
        columns: List[Dict] = []
        rows: List[list] = []
        qid = body.get("id", "")
        while True:
            if body.get("columns"):
                columns = body["columns"]
            rows.extend(body.get("data", []))
            err = body.get("error")
            nxt = body.get("nextUri")
            if err or nxt is None:
                return ClientResult(
                    columns=columns,
                    rows=rows,
                    state=body.get("stats", {}).get("state", "?"),
                    query_id=qid,
                    update_type=body.get("updateType"),
                    error=err,
                )
            if time.time() > deadline:
                raise TimeoutError(f"query {qid} timed out")
            body = self._request(nxt)
